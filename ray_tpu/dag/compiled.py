"""Compiled DAG: lower a node graph to static per-actor schedules over
shared-memory channels.

Mirrors the reference's compiled graphs (reference:
python/ray/dag/compiled_dag_node.py `CompiledDAG` :805, per-actor exec
loop `do_exec_tasks` :186, `execute()` :2546, `_execute_until` :2475;
op schedule dag_node_operation.py). The property preserved: after
compile there is **no task submission and no scheduler involvement** per
step — the driver writes the input channel, every actor spins in a
read→compute→write loop, and the driver reads the output channels.

TPU-native difference: on-device tensors never move through these host
channels in the hot path — a compiled JAX step stays on device inside one
actor, and device-to-device edges lower to XLA collectives via the
`collective` DAG nodes (allgather-based on the CPU backend for tests,
shard_map collectives on a mesh). The host channels carry control-plane
payloads and host arrays, like the reference's shared-memory channels.
"""

from __future__ import annotations

import itertools
import os
from typing import Any

import ray_tpu
from ray_tpu.collective.types import ReduceOp
from ray_tpu.dag.channel import ChannelClosed, ShmChannel
from ray_tpu.dag.node import (
    AttributeNode,
    ClassMethodNode,
    CollectiveNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

_dag_counter = itertools.count()


class _DagError:
    """Error value that flows through channels instead of raising
    mid-loop (reference: RayTaskError traveling through CompiledDAGRef)."""

    __slots__ = ("err",)

    def __init__(self, err: Exception):
        self.err = err


class CompiledDAGRef:
    """Future for one ``execute()`` call (reference:
    compiled_dag_ref.py)."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx

    def get(self, timeout: float | None = 30.0):
        return self._dag._result(self._idx, timeout)


class CompiledDAG:
    def __init__(
        self,
        root: DAGNode,
        *,
        buffer_size: int | None = None,
        max_buffered: int | None = None,
    ):
        from ray_tpu.dag.context import DAGContext

        ctx = DAGContext.get()
        self.buffer_size = buffer_size or ctx.buffer_size
        self.nslots = max_buffered or ctx.max_buffered
        self.dag_id = f"dag{next(_dag_counter)}_{os.getpid()}"
        self.root = root
        self._exec_idx = 0
        self._read_idx = 0
        self._row: list = []
        self._cache: dict[int, Any] = {}
        self._torn_down = False
        self._compile()

    # ---------------------------------------------------------- compile
    def _compile(self):
        # 1. Topo-collect nodes.
        order: list[DAGNode] = []
        seen: set[int] = set()

        def visit(n: DAGNode):
            if n.uid in seen:
                return
            seen.add(n.uid)
            for d in n.upstream():
                visit(d)
            order.append(n)

        visit(self.root)
        self.outputs = (
            list(self.root.args)
            if isinstance(self.root, MultiOutputNode)
            else [self.root]
        )

        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG can have at most one InputNode")
        self.has_input = bool(inputs)

        # 2. Owner of every node: actor id for method/collective nodes,
        #    None = driver for input; attribute nodes live with their
        #    parent's owner (extraction happens reader-side, see _expr).
        owner: dict[int, str | None] = {}
        actors: dict[str, Any] = {}
        for n in order:
            if isinstance(n, (InputNode,)):
                owner[n.uid] = None
            elif isinstance(n, AttributeNode):
                owner[n.uid] = owner[n.parent.uid]
            elif isinstance(n, ClassMethodNode):
                owner[n.uid] = n.actor._actor_id
                actors[n.actor._actor_id] = n.actor
            elif isinstance(n, CollectiveNode):
                parent_owner = owner[n.parent.uid]
                if parent_owner is None:
                    raise ValueError(
                        "collective input must come from an actor node"
                    )
                owner[n.uid] = parent_owner
            elif isinstance(n, MultiOutputNode):
                owner[n.uid] = None
            else:
                raise TypeError(type(n).__name__)
        self._owner = owner
        self._actors = actors

        # 3. Find cross-owner edges → each producer node gets one channel
        #    with one reader rank per consuming owner. `_source` maps any
        #    node to the channel-producing node it aliases (attribute
        #    nodes read their parent's channel).
        def source(n: DAGNode) -> DAGNode:
            while isinstance(n, AttributeNode):
                n = n.parent
            return n

        consumers: dict[int, set[str | None]] = {}  # producer uid → owners
        for n in order:
            if isinstance(n, MultiOutputNode):
                continue
            for dep in n.upstream():
                src = source(dep)
                if owner[src.uid] != owner[n.uid]:
                    consumers.setdefault(src.uid, set()).add(owner[n.uid])
        for out in self.outputs:
            src = source(out)
            if owner[src.uid] is None and not isinstance(
                source(out), InputNode
            ):
                raise ValueError("DAG outputs must be actor-produced nodes")
            consumers.setdefault(src.uid, set()).add(None)

        # 4. Allocate channel files (driver creates; everyone opens).
        base = os.path.join(
            ray_tpu.api._runtime.core.store.dir, "channels", self.dag_id
        )
        os.makedirs(base, exist_ok=True)
        self._chan_dir = base
        self._channels: dict[int, dict] = {}  # producer uid → spec
        node_by_uid = {n.uid: n for n in order}
        for uid, owners in consumers.items():
            readers = sorted(owners, key=lambda o: (o is None, o or ""))
            path = os.path.join(base, f"ch_{uid}")
            ShmChannel(
                path,
                writer=True,
                create=True,
                n_readers=len(readers),
                nslots=self.nslots,
                capacity=self.buffer_size,
            )
            self._channels[uid] = {
                "path": path,
                "readers": {o: r for r, o in enumerate(readers)},
                "producer": owner[uid],
            }

        # 5. Collective groups: one per op_id, ranks = bind order.
        groups: dict[int, list[str]] = {}  # op_id → actor ids in rank order
        for n in order:
            if isinstance(n, CollectiveNode):
                groups.setdefault(n.op_id, []).append(owner[n.uid])
        self._groups = {
            op_id: {
                "name": f"{self.dag_id}_col{op_id}",
                "members": members,
            }
            for op_id, members in groups.items()
        }

        # 6. Per-actor schedules in topo order.
        schedules: dict[str, list] = {a: [] for a in actors}
        for n in order:
            own = owner[n.uid]
            if own is None or isinstance(n, AttributeNode):
                continue
            if isinstance(n, ClassMethodNode):
                op = {
                    "kind": "method",
                    "uid": n.uid,
                    "method": n.method_name,
                    "args": [self._expr(a, own, node_by_uid) for a in n.args],
                    "kwargs": {
                        k: self._expr(v, own, node_by_uid)
                        for k, v in n.kwargs.items()
                    },
                }
            elif isinstance(n, CollectiveNode):
                g = self._groups[n.op_id]
                op = {
                    "kind": "collective",
                    "uid": n.uid,
                    "verb": n.kind,
                    "op": n.reduce_op.value,
                    "group": g["name"],
                    "rank": g["members"].index(own),
                    "world": len(g["members"]),
                    "perm": n.perm,
                    "args": [self._expr(n.parent, own, node_by_uid)],
                    "kwargs": {},
                }
            else:
                continue
            spec = self._channels.get(n.uid)
            op["write"] = (
                {"path": spec["path"]} if spec is not None else None
            )
            schedules[own].append(op)
        self._schedules = schedules

        # 7. Start actor loops: per actor, first a setup task (open
        #    channels + init collective groups), then the spinning loop.
        self._loop_refs = []
        for actor_id, schedule in schedules.items():
            handle = actors[actor_id]
            chan_specs = self._reader_specs(actor_id)
            group_specs = [
                {
                    "name": g["name"],
                    "world": len(g["members"]),
                    "rank": g["members"].index(actor_id),
                }
                for g in self._groups.values()
                if actor_id in g["members"]
            ]
            ref = _submit_system_task(
                handle,
                _dag_actor_loop,
                schedule,
                chan_specs,
                group_specs,
                self.nslots,
                self.buffer_size,
            )
            self._loop_refs.append(ref)

        # 8. Driver ends: input writer + output readers.
        if self.has_input:
            inp_uid = inputs[0].uid
            if inp_uid not in self._channels:
                raise ValueError("InputNode is never consumed by any actor")
            self._input_chan = ShmChannel(
                self._channels[inp_uid]["path"], writer=True
            )
        else:
            self._input_chan = None
        self._output_readers = []
        for out in self.outputs:
            src = source(out)
            spec = self._channels[src.uid]
            chan = ShmChannel(
                spec["path"], writer=False, rank=spec["readers"][None]
            )
            self._output_readers.append((chan, self._attr_chain(out)))

    def _expr(self, value, reader_owner, node_by_uid):
        """Encode an argument: const | read-from-channel | local value |
        input extraction. Attribute chains apply reader-side."""
        if not isinstance(value, DAGNode):
            return ("const", value)
        chain = self._attr_chain(value)
        src = value
        while isinstance(src, AttributeNode):
            src = src.parent
        if self._owner[src.uid] == reader_owner:
            return ("local", src.uid, chain)
        spec = self._channels[src.uid]
        return (
            "chan",
            src.uid,
            spec["path"],
            spec["readers"][reader_owner],
            chain,
            isinstance(src, InputNode),
        )

    @staticmethod
    def _attr_chain(n: DAGNode):
        chain = []
        while isinstance(n, AttributeNode):
            chain.append(n.key)
            n = n.parent
        chain.reverse()
        return chain

    def _reader_specs(self, actor_id):
        """All channels this actor reads, for the setup phase."""
        specs = []
        for uid, spec in self._channels.items():
            if actor_id in spec["readers"]:
                specs.append(
                    {
                        "uid": uid,
                        "path": spec["path"],
                        "rank": spec["readers"][actor_id],
                    }
                )
        return specs

    # ---------------------------------------------------------- execute
    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG has been torn down")
        if self._input_chan is not None:
            self._input_chan.write((args, kwargs))
        ref = CompiledDAGRef(self, self._exec_idx)
        self._exec_idx += 1
        return ref

    def _result(self, idx: int, timeout: float | None):
        while idx not in self._cache:
            # Resumable row: a timeout mid-row must not drop the reads
            # already done, or the output channels desynchronize.
            while len(self._row) < len(self._output_readers):
                chan, chain = self._output_readers[len(self._row)]
                v = chan.read(timeout=timeout)
                for key in chain:
                    if not isinstance(v, _DagError):
                        v = v[key]
                self._row.append(v)
            self._cache[self._read_idx] = self._row
            self._row = []
            self._read_idx += 1
        values = self._cache.pop(idx)
        for v in values:
            if isinstance(v, _DagError):
                raise v.err
        return values[0] if len(values) == 1 else values

    # --------------------------------------------------------- teardown
    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        if self._input_chan is not None:
            self._input_chan.close()
        for chan, _ in self._output_readers:
            chan.close()
        try:
            ray_tpu.get(self._loop_refs, timeout=10)
        # tpulint: allow(broad-except reason=teardown join: loop actors may already be dead or killed, which is exactly what teardown wants)
        except Exception:  # noqa: BLE001 - actors may already be dead
            pass
        import shutil

        shutil.rmtree(self._chan_dir, ignore_errors=True)

    def __del__(self):
        try:
            self.teardown()
        # tpulint: allow(broad-except reason=__del__ during interpreter shutdown: modules may be half-torn-down and raising would print an unraisable-exception warning, not propagate)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


# ------------------------------------------------------- actor-side loop
def _submit_system_task(handle, fn, *args):
    """Run ``fn(instance, *args)`` as an actor task (the @sys: dispatch in
    core_worker._execute)."""
    from ray_tpu.api import _submit_system_task as submit

    return submit(handle, fn, *args)


def _dag_actor_loop(
    instance, schedule, chan_specs, group_specs, nslots, buffer_size,
):
    """The compiled per-actor loop (reference: do_exec_tasks
    compiled_dag_node.py:186 — READ → COMPUTE → WRITE until teardown).
    Runs on the actor's execution thread; channel waits are busy-polls on
    shared memory, not RPCs.

    The reference's overlapped execution schedule
    (dag_node_operation.py:576-593) exists to hide NCCL transfer latency
    behind GPU compute. A host-thread equivalent (prefetch + writer
    threads around these channels) was built, benchmarked net-negative
    at BOTH small and 8 MiB payloads — the GIL serializes the copies
    with compute, and the ShmChannel ring already pipelines ACROSS
    actors — and deleted; device tensors move through tensor transport /
    collective permute instead (PERF.json dag row)."""
    import numpy as np

    import ray_tpu.collective as col

    # setup: open read/write ends, init collective groups
    readers = {
        s["uid"]: ShmChannel(s["path"], writer=False, rank=s["rank"])
        for s in chan_specs
    }
    writers = {}
    for op in schedule:
        if op["write"] is not None:
            writers[op["uid"]] = ShmChannel(op["write"]["path"], writer=True)
    for g in group_specs:
        if not col.is_group_initialized(g["name"]):
            col.init_collective_group(
                g["world"], g["rank"], backend="cpu", group_name=g["name"]
            )

    # Channel-read order per iteration (deterministic): first use of each
    # channel in schedule order.
    read_order: list[int] = []
    for op in schedule:
        for e in list(op["args"]) + list(op["kwargs"].values()):
            if e[0] == "chan" and e[1] not in read_order:
                read_order.append(e[1])

    def ensure_read(expr, env):
        """Advance the channel cursor for this op's inputs BEFORE any
        fallible extraction: a failed attribute chain must not leave a
        channel unread for the iteration, or every later iteration pairs
        mismatched values across channels."""
        if expr[0] == "chan" and expr[1] not in env:
            env[expr[1]] = readers[expr[1]].read()

    def eval_arg(expr, env):
        kind = expr[0]
        if kind == "const":
            return expr[1]
        if kind == "local":
            _, uid, chain = expr
            v = env[uid]
        else:
            _, uid, _path, _rank, chain, is_input = expr
            v = env[uid]
            if is_input and not isinstance(v, _DagError):
                in_args, in_kwargs = v
                if chain:
                    key = chain[0]
                    v = in_kwargs[key] if isinstance(key, str) else in_args[key]
                    chain = chain[1:]
                else:
                    v = in_args[0] if len(in_args) == 1 else in_args
        for key in chain:
            if isinstance(v, _DagError):
                break
            v = v[key]
        return v

    def run_collective(op, value):
        """All collective verbs lower to allgather on the group, then a
        local reduce — error values gather like any payload, so a failed
        peer poisons the op instead of hanging it."""
        gathered = col.allgather(value, group_name=op["group"])
        # The CPU backend np.asarray-wraps payloads; a _DagError comes
        # back as a 0-d object array — unwrap before the error scan.
        gathered = [
            g.item()
            if isinstance(g, np.ndarray) and g.dtype == object and g.ndim == 0
            else g
            for g in gathered
        ]
        err = next((g for g in gathered if isinstance(g, _DagError)), None)
        if err is not None:
            return err
        if op["verb"] == "allgather":
            return list(gathered)
        if op["verb"] == "permute":
            # P2P rank rotation (pipeline stage handoff). Host payloads
            # lower via the gather (every value visible, pick my
            # source); device arrays never ride this path — they move
            # through tensor transport / XlaMeshGroup.permute
            # (lax.ppermute over ICI) on a mesh.
            src = next(
                (s for s, d in op["perm"] if d == op["rank"]), None
            )
            return None if src is None else gathered[src]
        stack = np.stack([np.asarray(g) for g in gathered])
        reduced = {
            "sum": lambda: stack.sum(0),
            "product": lambda: stack.prod(0),
            "min": lambda: stack.min(0),
            "max": lambda: stack.max(0),
        }[ReduceOp(op["op"]).value]()
        if op["verb"] == "allreduce":
            return reduced
        return np.array_split(reduced, op["world"], axis=0)[op["rank"]]

    try:
        while True:
            env: dict[int, Any] = {}
            for op in schedule:
                for e in list(op["args"]) + list(op["kwargs"].values()):
                    ensure_read(e, env)  # ChannelClosed propagates
                try:
                    args = [eval_arg(e, env) for e in op["args"]]
                    kwargs = {
                        k: eval_arg(e, env) for k, e in op["kwargs"].items()
                    }
                    err = next(
                        (
                            a
                            for a in list(args) + list(kwargs.values())
                            if isinstance(a, _DagError)
                        ),
                        None,
                    )
                    if op["kind"] == "collective":
                        value = run_collective(op, args[0])
                    elif err is not None:
                        value = err
                    else:
                        value = getattr(instance, op["method"])(
                            *args, **kwargs
                        )
                except ChannelClosed:
                    raise
                # tpulint: allow(broad-except reason=the exception is captured as a typed _DagError and flows through the output channel to the caller, who re-raises it)
                except Exception as e:  # noqa: BLE001 - flows to output
                    value = _DagError(e)
                env[op["uid"]] = value
                w = writers.get(op["uid"])
                if w is not None:
                    w.write(value)
    except ChannelClosed:
        pass
    finally:
        for w in writers.values():
            w.close()
        for g in group_specs:
            try:
                col.destroy_collective_group(g["name"])
            # tpulint: allow(broad-except reason=loop teardown of per-execution groups; a poisoned or already-destroyed group raises typed errors with nothing left to clean)
            except Exception:  # noqa: BLE001
                pass
    return {"ok": True}
