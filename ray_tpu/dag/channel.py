"""Shared-memory channels for compiled graphs.

The reference's compiled-graph transport on one node is a *mutable plasma
object*: a fixed shared-memory slot with write-acquire / read-release
semantics (reference: src/ray/core_worker/experimental_mutable_object_manager.h:44,
python/ray/experimental/channel/shared_memory_channel.py). The TPU-native
equivalent keeps the idea but is a lock-free single-writer / multi-reader
ring over one mmap'd file in the node's /dev/shm session directory: the
writer publishes by bumping a 64-bit write counter; each reader owns a
64-bit read counter; backpressure = the writer waits while
``write_count - min(read_counts) == nslots``. Payloads that exceed the
slot capacity spill to a side file whose name is embedded in the slot
(the analogue of plasma's fallback allocation).

No daemons, no locks: on x86/ARM64 the aligned 8-byte counter stores are
single machine stores and the payload is written strictly before the
counter bump (TSO / release ordering is sufficient for SPMC here).
"""

from __future__ import annotations

import mmap
import os
import struct
import time

from ray_tpu._private.serialization import deserialize, serialize

_MAGIC = 0x5254_5055_4348_414E  # "RTPUCHAN"
# header: magic u64 | nslots u32 | n_readers u32 | capacity u64 | closed u64
_HEADER = struct.Struct("<QIIQQ")
_U64 = struct.Struct("<Q")
# per-slot record header: data_len u64 | spill u32 | pad u32
_SLOT = struct.Struct("<QII")
_ALIGN = 64

DEFAULT_CAPACITY = 256 * 1024
DEFAULT_NSLOTS = 8


class ChannelClosed(Exception):
    """Raised by read/write after the peer has torn the channel down."""


class ChannelTimeout(Exception):
    pass


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class _Wait:
    """Spin-then-sleep poll loop (the hot path is the spin: same-host
    handoff latency stays in the microseconds)."""

    __slots__ = ("spins", "deadline")

    def __init__(self, timeout: float | None):
        self.spins = 0
        self.deadline = None if timeout is None else time.monotonic() + timeout

    def step(self):
        self.spins += 1
        if self.spins < 200:
            pass  # pure spin
        elif self.spins < 1000:
            time.sleep(0)
        else:
            time.sleep(0.0002)
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise ChannelTimeout("channel wait timed out")


class ShmChannel:
    """One file = one channel. The creating side picks geometry; writer
    and readers both ``open`` it by path. ``rank`` selects the reader
    cursor; the writer passes ``rank=None``.
    """

    def __init__(
        self,
        path: str,
        *,
        writer: bool,
        rank: int | None = None,
        create: bool = False,
        n_readers: int = 1,
        nslots: int = DEFAULT_NSLOTS,
        capacity: int = DEFAULT_CAPACITY,
    ):
        self.path = path
        self.writer = writer
        self.rank = rank
        if create:
            self._create(n_readers, nslots, capacity)
        self._open()

    # ------------------------------------------------------------ layout
    def _create(self, n_readers: int, nslots: int, capacity: int):
        slot_stride = _aligned(_SLOT.size + capacity)
        counters_off = _aligned(_HEADER.size)
        slots_off = _aligned(counters_off + 8 * (1 + n_readers))
        total = slots_off + nslots * slot_stride
        tmp = f"{self.path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.truncate(total)
            f.seek(0)
            f.write(_HEADER.pack(_MAGIC, nslots, n_readers, capacity, 0))
        os.rename(tmp, self.path)

    def _open(self):
        wait = _Wait(timeout=30.0)
        while not os.path.exists(self.path):
            wait.step()
        fd = os.open(self.path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            self._m = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, nslots, n_readers, capacity, _ = _HEADER.unpack_from(self._m, 0)
        if magic != _MAGIC:
            raise ValueError(f"not a channel file: {self.path}")
        self.nslots = nslots
        self.n_readers = n_readers
        self.capacity = capacity
        self._counters_off = _aligned(_HEADER.size)
        self._slots_off = _aligned(self._counters_off + 8 * (1 + n_readers))
        self._slot_stride = _aligned(_SLOT.size + capacity)
        # local cursor mirrors the shared one (cheap reads)
        self._count = self._read_u64(self._counters_off) if self.writer else (
            0 if self.rank is None else self._read_u64(self._reader_off(self.rank))
        )

    # ------------------------------------------------------- tiny atomics
    def _read_u64(self, off: int) -> int:
        return _U64.unpack_from(self._m, off)[0]

    def _write_u64(self, off: int, value: int):
        _U64.pack_into(self._m, off, value)

    def _reader_off(self, rank: int) -> int:
        return self._counters_off + 8 * (1 + rank)

    @property
    def _write_count(self) -> int:
        return self._read_u64(self._counters_off)

    @property
    def closed(self) -> bool:
        return _HEADER.unpack_from(self._m, 0)[4] != 0

    def close(self):
        """Mark closed; blocked peers wake up and raise ChannelClosed."""
        header = list(_HEADER.unpack_from(self._m, 0))
        header[4] = 1
        _HEADER.pack_into(self._m, 0, *header)

    def destroy(self):
        self.close()
        try:
            self._m.close()
        except BufferError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # ------------------------------------------------------------- write
    def _min_read_count(self) -> int:
        return min(
            self._read_u64(self._reader_off(r)) for r in range(self.n_readers)
        )

    def write(self, value, timeout: float | None = None):
        if not self.writer:
            raise RuntimeError("read end of channel cannot write")
        blob = _pack(value)
        count = self._count
        wait = _Wait(timeout)
        while count - self._min_read_count() >= self.nslots:
            if self.closed:
                raise ChannelClosed(self.path)
            wait.step()
        if self.closed:
            raise ChannelClosed(self.path)
        slot_off = self._slots_off + (count % self.nslots) * self._slot_stride
        old_spill = self._spill_path(count - self.nslots)
        if os.path.exists(old_spill):
            os.unlink(old_spill)
        if len(blob) <= self.capacity:
            _SLOT.pack_into(self._m, slot_off, len(blob), 0, 0)
            self._m[
                slot_off + _SLOT.size : slot_off + _SLOT.size + len(blob)
            ] = blob
        else:
            spill = self._spill_path(count)
            tmp = spill + ".w"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.rename(tmp, spill)
            _SLOT.pack_into(self._m, slot_off, 0, 1, 0)
        self._count = count + 1
        self._write_u64(self._counters_off, self._count)  # publish

    def _spill_path(self, count: int) -> str:
        return f"{self.path}.sp{count % (2 * self.nslots)}"

    # -------------------------------------------------------------- read
    def read(self, timeout: float | None = None):
        if self.writer or self.rank is None:
            raise RuntimeError("write end of channel cannot read")
        count = self._count
        wait = _Wait(timeout)
        while self._write_count <= count:
            if self.closed:
                raise ChannelClosed(self.path)
            wait.step()
        slot_off = self._slots_off + (count % self.nslots) * self._slot_stride
        data_len, spill, _ = _SLOT.unpack_from(self._m, slot_off)
        if spill:
            with open(self._spill_path(count), "rb") as f:
                blob = f.read()
        else:
            blob = bytes(
                self._m[
                    slot_off + _SLOT.size : slot_off + _SLOT.size + data_len
                ]
            )
        value = _unpack(blob)
        self._count = count + 1
        self._write_u64(self._reader_off(self.rank), self._count)  # release
        return value


# ------------------------------------------------------- serialization
_BLOB = struct.Struct("<I")


def _pack(value) -> bytes:
    s = serialize(value).materialize_buffers()
    parts = [_BLOB.pack(len(s.buffers) + 1), _U64.pack(len(s.inband)), s.inband]
    for b in s.buffers:
        parts.append(_U64.pack(len(b)))
        parts.append(bytes(b) if not isinstance(b, bytes) else b)
    return b"".join(parts)


def _unpack(blob: bytes):
    (n,) = _BLOB.unpack_from(blob, 0)
    off = _BLOB.size
    parts = []
    for _ in range(n):
        (length,) = _U64.unpack_from(blob, off)
        off += _U64.size
        parts.append(blob[off : off + length])
        off += length
    return deserialize(parts[0], parts[1:])


class IntraProcessChannel:
    """Driver-local channel (no shm needed): plain deque with the same
    read/write/close surface, used when producer and consumer share a
    process (reference: channel/intra_process_channel.py)."""

    def __init__(self):
        from collections import deque

        self._q = deque()
        self._closed = False

    def write(self, value, timeout: float | None = None):
        if self._closed:
            raise ChannelClosed("intra-process channel closed")
        self._q.append(value)

    def read(self, timeout: float | None = None):
        wait = _Wait(timeout)
        while not self._q:
            if self._closed:
                raise ChannelClosed("intra-process channel closed")
            wait.step()
        return self._q.popleft()

    def close(self):
        self._closed = True
