"""DAG execution knobs (reference: python/ray/dag/context.py
`DAGContext` — buffer size, max buffered results, timeouts). Values
resolve through the central config registry at CONSTRUCTION time, so
``init(_system_config=...)`` overrides apply even when this module was
imported earlier."""

from __future__ import annotations

from dataclasses import dataclass, field


def _cfg(name: str):
    from ray_tpu._private import config

    return config.get(name)


@dataclass
class DAGContext:
    buffer_size: int = field(
        default_factory=lambda: _cfg("DAG_BUFFER_SIZE")
    )
    max_buffered: int = field(
        default_factory=lambda: _cfg("DAG_MAX_BUFFERED")
    )
    submit_timeout: float = field(
        default_factory=lambda: _cfg("DAG_SUBMIT_TIMEOUT")
    )
    get_timeout: float = field(
        default_factory=lambda: _cfg("DAG_GET_TIMEOUT")
    )

    _instance = None

    @classmethod
    def get(cls) -> "DAGContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
