"""DAG execution knobs (reference: python/ray/dag/context.py
`DAGContext` — buffer size, max buffered results, timeouts; env-var
overridable the same way)."""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class DAGContext:
    buffer_size: int = int(
        os.environ.get("RAY_TPU_DAG_BUFFER_SIZE", 256 * 1024)
    )
    max_buffered: int = int(os.environ.get("RAY_TPU_DAG_MAX_BUFFERED", 8))
    submit_timeout: float = float(
        os.environ.get("RAY_TPU_DAG_SUBMIT_TIMEOUT", 30.0)
    )
    get_timeout: float = float(os.environ.get("RAY_TPU_DAG_GET_TIMEOUT", 30.0))

    _instance = None

    @classmethod
    def get(cls) -> "DAGContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
