"""Minimal vectorizable env API + built-in envs (numpy, no gym dependency).

The reference wraps external gymnasium envs (reference:
rllib/env/env_runner.py, rllib/examples use gym.make); this image ships no
gym, so the framework defines the same reset/step surface and registers
envs by name. User envs implementing this protocol plug into
:class:`ray_tpu.rl.EnvRunnerGroup` unchanged.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


class Env:
    """Single-episode env protocol: reset() -> obs, step(a) -> (obs, r, done)."""

    observation_size: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> tuple[np.ndarray, float, bool]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing, the reference test-suite workhorse.

    Dynamics follow the standard OpenAI formulation (Euler integration,
    force +-10N, fail at |x|>2.4 or |theta|>12deg, 500-step limit).
    """

    observation_size = 4
    num_actions = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * np.pi / 180
    MAX_STEPS = 500

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float64)
        self._t = 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32)

    def step(self, action: int) -> tuple[np.ndarray, float, bool]:
        x, x_dot, th, th_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pole_ml * th_dot**2 * sin) / total_mass
        th_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos**2 / total_mass)
        )
        x_acc = temp - pole_ml * th_acc * cos / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        th += self.DT * th_dot
        th_dot += self.DT * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        done = (
            abs(x) > self.X_LIMIT
            or abs(th) > self.THETA_LIMIT
            or self._t >= self.MAX_STEPS
        )
        return self._state.astype(np.float32), 1.0, bool(done)


class ChainEnv(Env):
    """Deterministic N-state chain: action 1 moves right (+1 reward at the
    end), action 0 resets to the start. Trivially learnable — used by fast
    tests the way the reference uses toy envs in rllib/examples."""

    num_actions = 2

    def __init__(self, n: int = 8, seed: int = 0):
        self.n = n
        self.observation_size = n
        self._pos = 0

    def _obs(self) -> np.ndarray:
        obs = np.zeros(self.n, np.float32)
        obs[self._pos] = 1.0
        return obs

    def reset(self, seed: int | None = None) -> np.ndarray:
        self._pos = 0
        return self._obs()

    def step(self, action: int) -> tuple[np.ndarray, float, bool]:
        if action == 1:
            self._pos += 1
        else:
            self._pos = 0
        if self._pos >= self.n - 1:
            return self._obs(), 1.0, True
        return self._obs(), 0.0, False


_REGISTRY: dict[str, Callable[..., Env]] = {}


def register_env(name: str, creator: Callable[..., Env]) -> None:
    """Register an env constructor under a string id (reference:
    rllib `tune.register_env`)."""
    _REGISTRY[name] = creator


def make_env(name: str, **kwargs) -> Env:
    if name not in _REGISTRY:
        raise KeyError(f"unknown env {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


register_env("CartPole", CartPole)
register_env("Chain", ChainEnv)
