"""Offline RL as a DATA pipeline: episode recording to parquet,
dataset-fed behavior cloning, and offline evaluation.

Reference: rllib/offline/offline_data.py — the reference records
rollouts as episode files, reads them back through Ray Data
(sampling/shuffling handled by the dataset layer, not the algorithm),
and evaluates offline-trained policies. Here the same three pieces ride
``ray_tpu.data``: :func:`record_rollouts` writes transition rows
through ``Dataset.write_parquet``; :class:`OfflineBCConfig` trains BC
from those files via ``read_parquet`` + shuffled windowed
``iter_batches``; :func:`evaluate_policy` rolls the cloned policy in a
live env and reports it against the dataset's own behavior returns.

Episode schema (one row per transition, flat columns so parquet stays
columnar): eps_id, t, obs (float list), action, reward, done.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.env import make_env


def record_rollouts(
    algo: Algorithm, path: str, *, num_rounds: int = 1
) -> dict:
    """Sample the algorithm's env runners ``num_rounds`` times and
    write every transition to parquet under ``path`` (the recording
    half of the reference's offline_data pipeline). Returns a summary
    {rows, episodes, mean_episode_return}."""
    import ray_tpu.data as rdata

    rows: list[dict] = []
    eps_counter = 0
    ep_returns: list[float] = []
    for _ in range(num_rounds):
        algo.runners.set_weights(algo.learner.get_weights())
        for sample in algo.runners.sample():
            obs = sample["obs"]  # [T, N, D]
            acts = sample["actions"]
            rews = sample["rewards"]
            dones = sample["dones"]
            T, N = acts.shape[:2]
            # Per env-slot episode ids: a done splits episodes.
            for n in range(N):
                eps_id = eps_counter
                eps_counter += 1
                t_in_ep = 0
                ep_ret = 0.0
                for t in range(T):
                    rows.append(
                        {
                            "eps_id": int(eps_id),
                            "t": int(t_in_ep),
                            "obs": [float(x) for x in obs[t, n]],
                            "action": int(acts[t, n]),
                            "reward": float(rews[t, n]),
                            "done": bool(dones[t, n]),
                        }
                    )
                    ep_ret += float(rews[t, n])
                    t_in_ep += 1
                    if dones[t, n]:
                        ep_returns.append(ep_ret)
                        ep_ret = 0.0
                        eps_id = eps_counter
                        eps_counter += 1
                        t_in_ep = 0
    ds = rdata.from_items(rows)
    ds.write_parquet(path)
    return {
        "rows": len(rows),
        "episodes": len(ep_returns),
        "mean_episode_return": (
            float(np.mean(ep_returns)) if ep_returns else float("nan")
        ),
    }


def dataset_report(path: str) -> dict:
    """Behavior statistics of a recorded dataset (the baseline an
    offline-trained policy is judged against)."""
    import ray_tpu.data as rdata

    ds = rdata.read_parquet(path)
    n = ds.count()
    # Episode returns: sum rewards per eps_id.
    returns = [
        row["sum(reward)"]
        for row in ds.groupby("eps_id").sum("reward").take_all()
    ]
    completed = ds.filter(lambda r: r["done"]).count()
    return {
        "rows": n,
        "episodes_started": len(returns),
        "episodes_completed": completed,
        "behavior_return_mean": float(np.mean(returns)),
    }


def evaluate_policy(
    module, params, env_name: str, *, env_kwargs=None,
    n_episodes: int = 20, max_steps: int = 200, seed: int = 0,
    greedy: bool = True,
) -> dict:
    """Roll the policy in a live env (the online half of offline
    evaluation; reference: offline RL evaluation rollouts)."""
    import jax

    fwd = jax.jit(module.forward, backend="cpu")
    rng = np.random.default_rng(seed)
    returns = []
    for ep in range(n_episodes):
        env = make_env(env_name, **(env_kwargs or {}))
        obs = env.reset(seed + ep)
        total = 0.0
        for _ in range(max_steps):
            out = fwd(params, obs[None])
            logits = np.asarray(out["logits"])[0]
            if greedy:
                a = int(logits.argmax())
            else:
                z = logits - logits.max()
                p = np.exp(z) / np.exp(z).sum()
                a = int(rng.choice(len(p), p=p))
            obs, r, done = env.step(a)
            total += float(r)
            if done:
                break
        returns.append(total)
    return {
        "episodes": n_episodes,
        "return_mean": float(np.mean(returns)),
        "return_min": float(np.min(returns)),
        "return_max": float(np.max(returns)),
    }


from ray_tpu.rl.bc import BCConfig  # noqa: E402


@dataclass(frozen=True)
class OfflineBCConfig(BCConfig):
    """BC fed from recorded parquet episodes through ray_tpu.data
    (reference: BC with input_=dataset paths via offline_data.py).
    ``input_path`` replaces the in-memory ``dataset`` dict; each
    epoch re-shuffles the dataset and iterates windowed batches."""

    input_path: str = ""
    shuffle_seed: int = 0

    def build(self) -> "OfflineBC":
        return OfflineBC(self)


class OfflineBC:
    """Dataset-driven BC: the training loop pulls shuffled windowed
    batches from the data pipeline instead of indexing a numpy dict."""

    def __init__(self, config: OfflineBCConfig):
        if not config.input_path:
            raise ValueError("OfflineBCConfig.input_path is required")
        import ray_tpu.data as rdata

        from ray_tpu.rl.algorithm import make_adam
        from ray_tpu.rl.bc import bc_loss
        from ray_tpu.rl.learner import Learner
        from ray_tpu.rl.module import MLPModule

        self.config = config
        self._ds = rdata.read_parquet(config.input_path)
        probe = self._ds.take(1)[0]
        obs_size = len(probe["obs"])
        num_actions = (
            int(
                self._ds.max("action")
            )
            + 1
        )
        self.module = MLPModule(
            observation_size=obs_size, num_actions=num_actions
        )
        self.learner = Learner(
            self.module, bc_loss, make_adam(config.lr),
            mesh=config.mesh, seed=config.seed,
        )
        self.iteration = 0
        self._epoch = 0
        self._batches = self._epoch_batches()

    def _epoch_batches(self):
        """One epoch: reshuffle (a fresh seed per epoch) and iterate
        windowed batches — the dataset layer does the shuffling, the
        algorithm just consumes (reference: offline_data windowed
        iteration)."""
        self._epoch += 1
        return self._ds.random_shuffle(
            seed=self.config.shuffle_seed + self._epoch
        ).iter_batches(
            batch_size=self.config.batch_size, batch_format="numpy"
        )

    def _next_batch(self) -> dict:
        while True:
            batch = next(self._batches, None)
            if batch is not None:
                return batch
            self._batches = self._epoch_batches()

    def train(self) -> dict:
        cfg = self.config
        metrics: dict = {}
        for _ in range(cfg.updates_per_step):
            b = self._next_batch()
            obs = np.stack([np.asarray(o, np.float32) for o in b["obs"]])
            metrics = self.learner.update(
                {
                    "obs": obs,
                    "actions": np.asarray(b["action"], np.int64),
                }
            )
        self.iteration += 1
        metrics["epoch"] = self._epoch
        return {
            k: float(v) if hasattr(v, "item") else v
            for k, v in metrics.items()
        }

    def get_policy(self):
        return self.module, self.learner.params
