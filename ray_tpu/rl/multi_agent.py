"""Multi-agent RL: env protocol, module dict, policy mapping, PPO.

Reference: rllib/env/multi_agent_env.py (dict-keyed obs/actions/rewards
with a ``__all__`` done flag), rllib/core/rl_module/multi_rl_module.py
(a dict of RLModules keyed by module/policy id), and the
``policy_mapping_fn`` contract (algorithm_config.multi_agent(...)):
each agent id maps to a policy id; agents sharing a policy share
parameters and training batches.

TPU-native shape: rollouts stay on CPU numpy like the single-agent
runners; the learner side is one jitted update per POLICY (policies
are independent optimization problems — a dict of Learners, not one
padded program), so two policies of different obs sizes never force a
ragged batch through XLA.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ray_tpu.rl.env import Env, make_env
from ray_tpu.rl.module import MLPModule, RLModule, params_to_numpy


class MultiAgentEnv:
    """Dict-keyed episode protocol (reference: MultiAgentEnv.reset /
    step returning per-agent dicts; dones carry ``__all__``).

    Agents are FIXED for the episode (possibly_agents == agents): every
    dict is keyed by the full agent id set each step. Per-agent dones
    mark agents whose episode slice ended; ``__all__`` resets the env.
    """

    agent_ids: tuple[str, ...]
    observation_sizes: dict[str, int]
    num_actions: dict[str, int]

    def reset(self, seed: int | None = None) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def step(
        self, actions: dict[str, int]
    ) -> tuple[dict, dict, dict]:
        """-> (obs, rewards, dones) dicts; dones includes "__all__"."""
        raise NotImplementedError


class MultiChain(MultiAgentEnv):
    """N agents, each walking its own deterministic chain (the
    multi-agent analogue of the single-agent ChainEnv toy): action 1
    advances, action 0 resets to the start; +1 at the chain's end.
    Chains may differ in length per agent, so per-policy observation
    sizes genuinely differ — the shape mismatch a shared-vs-independent
    policy test needs. The episode ends when every agent has finished.
    """

    def __init__(self, lengths: "tuple[int, ...]" = (6, 6), seed: int = 0):
        self.agent_ids = tuple(f"agent_{i}" for i in range(len(lengths)))
        self._chains = {
            aid: make_env("Chain", n=n)
            for aid, n in zip(self.agent_ids, lengths)
        }
        self.observation_sizes = {
            aid: e.observation_size for aid, e in self._chains.items()
        }
        self.num_actions = {
            aid: e.num_actions for aid, e in self._chains.items()
        }
        self._done: dict[str, bool] = {}

    def reset(self, seed: int | None = None) -> dict[str, np.ndarray]:
        self._done = {aid: False for aid in self.agent_ids}
        return {
            aid: e.reset(seed) for aid, e in self._chains.items()
        }

    def step(self, actions: dict[str, int]):
        obs, rewards, dones = {}, {}, {}
        for aid, env in self._chains.items():
            if self._done[aid]:
                # Finished agents idle at their terminal obs with zero
                # reward until __all__ (reference: agents absent from
                # the step dicts once done; fixed-key dicts keep the
                # batch shapes static instead).
                obs[aid] = env._obs()
                rewards[aid] = 0.0
                dones[aid] = True
                continue
            o, r, d = env.step(int(actions[aid]))
            obs[aid], rewards[aid], dones[aid] = o, float(r), bool(d)
            self._done[aid] = bool(d)
        dones["__all__"] = all(self._done.values())
        return obs, rewards, dones


_MA_ENVS: dict[str, Callable[..., MultiAgentEnv]] = {
    "MultiChain": MultiChain,
}


def register_multi_agent_env(name: str, creator) -> None:
    _MA_ENVS[name] = creator


def make_multi_agent_env(name: str, **kwargs) -> MultiAgentEnv:
    if name not in _MA_ENVS:
        raise KeyError(
            f"unknown multi-agent env {name!r}; registered: "
            f"{sorted(_MA_ENVS)}"
        )
    return _MA_ENVS[name](**kwargs)


@dataclass(frozen=True)
class MultiAgentSpec:
    """Policies + the agent→policy mapping (reference: the
    config.multi_agent(policies=..., policy_mapping_fn=...) pair and
    MultiRLModule's module dict)."""

    modules: "dict[str, RLModule]"
    policy_mapping_fn: Callable[[str], str]

    def policy_of(self, agent_id: str) -> str:
        pid = self.policy_mapping_fn(agent_id)
        if pid not in self.modules:
            raise KeyError(
                f"policy_mapping_fn({agent_id!r}) -> {pid!r}, which is "
                f"not in the module dict {sorted(self.modules)}"
            )
        return pid


class MultiAgentEnvRunner:
    """Rollout worker over vectorized multi-agent envs: per step, group
    observations BY POLICY, run one forward per policy, scatter actions
    back — the env-side half of the reference's multi-agent EnvRunner.
    Returns one [T, slots] batch per policy (slots = env copies x
    agents mapped to that policy)."""

    def __init__(
        self,
        env_name: str,
        env_kwargs: dict,
        spec: MultiAgentSpec,
        num_envs: int,
        rollout_len: int,
        seed: int,
    ):
        import jax

        self.spec = spec
        self.rollout_len = rollout_len
        self.envs = [
            make_multi_agent_env(env_name, **env_kwargs)
            for _ in range(num_envs)
        ]
        self.agent_ids = self.envs[0].agent_ids
        # (env_i, agent_id) slots per policy, fixed for the runner's
        # lifetime: the policy's batch row order.
        self.slots: dict[str, list[tuple[int, str]]] = {
            pid: [] for pid in spec.modules
        }
        for ei in range(num_envs):
            for aid in self.agent_ids:
                self.slots[spec.policy_of(aid)].append((ei, aid))
        self.obs = [
            e.reset(seed + i) for i, e in enumerate(self.envs)
        ]
        self.params: dict[str, dict] = {}
        self._rng = np.random.default_rng(seed)
        self._fwd = {
            pid: jax.jit(m.forward, backend="cpu")
            for pid, m in spec.modules.items()
        }
        self._ep_return = np.zeros(num_envs)
        self._completed: list[float] = []

    def set_weights(self, params: "dict[str, dict]") -> None:
        self.params = params

    def sample(self) -> "dict[str, dict]":
        """One rollout_len rollout; returns policy_id -> batch dict of
        [T, slots(, D)] arrays plus last_value for GAE bootstrap."""
        T = self.rollout_len
        out: dict[str, dict] = {}
        buf = {
            pid: {
                "obs": [], "actions": [], "logp": [], "values": [],
                "rewards": [], "dones": [],
            }
            for pid in self.slots
        }
        for _ in range(T):
            acts_per_env: list[dict[str, int]] = [
                {} for _ in self.envs
            ]
            step_cache: dict[str, tuple] = {}
            for pid, slots in self.slots.items():
                if not slots:
                    continue
                obs = np.stack(
                    [self.obs[ei][aid] for ei, aid in slots]
                )
                fwd = self._fwd[pid](self.params[pid], obs)
                logits = np.asarray(fwd["logits"])
                values = np.asarray(fwd["value"])
                z = logits - logits.max(-1, keepdims=True)
                p = np.exp(z)
                p /= p.sum(-1, keepdims=True)
                actions = np.array(
                    [
                        self._rng.choice(len(row), p=row)
                        for row in p
                    ]
                )
                logp = np.log(
                    p[np.arange(len(actions)), actions] + 1e-9
                )
                for (ei, aid), a in zip(slots, actions):
                    acts_per_env[ei][aid] = int(a)
                step_cache[pid] = (obs, actions, logp, values)
            rewards_per_env, dones_per_env = [], []
            for ei, env in enumerate(self.envs):
                obs, rew, done = env.step(acts_per_env[ei])
                self._ep_return[ei] += sum(
                    rew[aid] for aid in self.agent_ids
                )
                if done["__all__"]:
                    self._completed.append(self._ep_return[ei])
                    self._ep_return[ei] = 0.0
                    obs = env.reset()
                self.obs[ei] = obs
                rewards_per_env.append(rew)
                dones_per_env.append(done)
            for pid, slots in self.slots.items():
                if not slots:
                    continue
                obs_b, actions, logp, values = step_cache[pid]
                b = buf[pid]
                b["obs"].append(obs_b)
                b["actions"].append(actions)
                b["logp"].append(logp)
                b["values"].append(values)
                b["rewards"].append(
                    np.array(
                        [rewards_per_env[ei][aid] for ei, aid in slots]
                    )
                )
                b["dones"].append(
                    np.array(
                        [
                            float(dones_per_env[ei][aid])
                            for ei, aid in slots
                        ]
                    )
                )
        for pid, slots in self.slots.items():
            if not slots:
                continue
            b = buf[pid]
            last_obs = np.stack(
                [self.obs[ei][aid] for ei, aid in slots]
            )
            last_value = np.asarray(
                self._fwd[pid](self.params[pid], last_obs)["value"]
            )
            out[pid] = {
                "obs": np.stack(b["obs"]),
                "actions": np.stack(b["actions"]),
                "logp": np.stack(b["logp"]),
                "values": np.stack(b["values"]),
                "rewards": np.stack(b["rewards"]),
                "dones": np.stack(b["dones"]),
                "last_value": last_value,
            }
        out["episode_returns"] = self._completed
        self._completed = []
        return out


@dataclass(frozen=True)
class MultiAgentPPOConfig:
    """Multi-agent PPO over a module dict (reference: PPO +
    config.multi_agent(...)). Build with explicit modules, or let
    ``from_env`` derive one MLP policy per distinct mapped policy id
    with that policy's obs/action sizes."""

    env: str = "MultiChain"
    env_kwargs: dict = field(default_factory=dict)
    modules: "dict[str, RLModule] | None" = None
    policy_mapping_fn: Callable[[str], str] = staticmethod(
        lambda aid: aid  # independent: one policy per agent
    )
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_len: int = 32
    lr: float = 3e-3
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    ent_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 128
    seed: int = 0

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    def __init__(self, config: MultiAgentPPOConfig):
        import ray_tpu
        from ray_tpu.rl.algorithm import make_adam
        from ray_tpu.rl.learner import Learner
        from ray_tpu.rl.ppo import ppo_loss

        self.config = config
        probe = make_multi_agent_env(config.env, **config.env_kwargs)
        modules = config.modules
        if modules is None:
            # One MLP per distinct policy id, sized from any agent
            # mapped to it (agents sharing a policy must share shapes).
            modules = {}
            for aid in probe.agent_ids:
                pid = config.policy_mapping_fn(aid)
                if pid not in modules:
                    modules[pid] = MLPModule(
                        observation_size=probe.observation_sizes[aid],
                        num_actions=probe.num_actions[aid],
                    )
        self.spec = MultiAgentSpec(modules, config.policy_mapping_fn)
        # Shared-policy shape check: every agent mapped to a policy
        # must produce that policy's obs size.
        for aid in probe.agent_ids:
            pid = self.spec.policy_of(aid)
            want = getattr(modules[pid], "observation_size", None)
            if want is not None and probe.observation_sizes[aid] != want:
                raise ValueError(
                    f"agent {aid!r} (obs {probe.observation_sizes[aid]}) "
                    f"maps to policy {pid!r} expecting obs {want}"
                )
        cfg = config

        def loss(params, module, batch):
            return ppo_loss(
                params, module, batch,
                cfg.clip_eps, cfg.vf_coeff, cfg.ent_coeff,
            )

        self.learners = {
            pid: Learner(m, loss, make_adam(cfg.lr), seed=cfg.seed + i)
            for i, (pid, m) in enumerate(sorted(modules.items()))
        }
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(
                cfg.env,
                cfg.env_kwargs,
                self.spec,
                cfg.num_envs_per_runner,
                cfg.rollout_len,
                cfg.seed + 1000 * i,
            )
            for i in range(cfg.num_env_runners)
        ]
        self.iteration = 0
        self._episode_returns: list[float] = []
        self._broadcast()

    def _broadcast(self) -> None:
        import ray_tpu

        weights = {
            pid: params_to_numpy(ln.params)
            for pid, ln in self.learners.items()
        }
        ray_tpu.get(
            [r.set_weights.remote(weights) for r in self.runners]
        )

    def train(self) -> dict:
        """One iteration: sample every runner, per-policy GAE +
        minibatch PPO updates, broadcast fresh weights. Returns per-
        policy metrics plus episode_return_mean."""
        import ray_tpu

        from ray_tpu.rl.ppo import compute_gae

        cfg = self.config
        samples = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for s in samples:
            self._episode_returns.extend(s.pop("episode_returns", []))
        self._episode_returns = self._episode_returns[-100:]
        metrics: dict = {}
        rng = np.random.default_rng(cfg.seed + self.iteration)
        for pid, learner in self.learners.items():
            parts = [s[pid] for s in samples if pid in s]
            if not parts:
                continue
            obs, acts, logp, advs, rets = [], [], [], [], []
            for s in parts:
                adv, ret = compute_gae(
                    s["rewards"], s["values"], s["dones"],
                    s["last_value"], cfg.gamma, cfg.gae_lambda,
                )
                obs.append(s["obs"].reshape(-1, s["obs"].shape[-1]))
                acts.append(s["actions"].reshape(-1))
                logp.append(s["logp"].reshape(-1))
                advs.append(adv.reshape(-1))
                rets.append(ret.reshape(-1))
            obs = np.concatenate(obs)
            acts = np.concatenate(acts)
            logp = np.concatenate(logp)
            advs = np.concatenate(advs)
            rets = np.concatenate(rets)
            advs = (advs - advs.mean()) / (advs.std() + 1e-8)
            n = len(obs)
            mb = min(cfg.minibatch_size, n)
            pm: dict = {}
            for _ in range(cfg.num_epochs):
                perm = rng.permutation(n)
                for start in range(0, n - mb + 1, mb):
                    idx = perm[start: start + mb]
                    pm = learner.update(
                        {
                            "obs": obs[idx],
                            "actions": acts[idx],
                            "logp_old": logp[idx],
                            "advantages": advs[idx],
                            "returns": rets[idx],
                        }
                    )
            pm["num_env_steps_sampled"] = n
            metrics[pid] = pm
        self._broadcast()
        self.iteration += 1
        metrics["episode_return_mean"] = (
            float(np.mean(self._episode_returns))
            if self._episode_returns
            else float("nan")
        )
        return metrics
