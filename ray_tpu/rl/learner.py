"""Learner: one pjit'd update program over the device mesh.

The reference scales learning with DDP across learner actors (reference:
rllib/core/learner/learner_group.py:101, torch DDP per learner); the
TPU-native shape is a single SPMD program — params replicated, batch
sharded on the mesh's dp axis — so gradient reduction is an XLA psum over
ICI instead of NCCL allreduce between processes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.rl.module import RLModule, params_to_numpy

LossFn = Callable[..., tuple[jnp.ndarray, dict]]


class Learner:
    """Owns params + optimizer state on device; update() runs the loss fn
    under jit with the batch sharded across `mesh`'s 'dp' axis."""

    def __init__(
        self,
        module: RLModule,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        mesh=None,
        seed: int = 0,
    ):
        self.module = module
        self.optimizer = optimizer
        self.mesh = mesh
        self.params = module.init(jax.random.key(seed))
        self.opt_state = optimizer.init(self.params)

        def _update(params, opt_state, batch, *extra):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, module, batch, *extra
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            aux["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, aux

        # No donation: callers may hold aliases of the param buffers (e.g.
        # DQN's target network) across updates.
        self._update = jax.jit(_update)

    def _shard_batch(self, batch: dict) -> dict:
        if self.mesh is None:
            return batch
        dp = self.mesh.shape.get("dp", 1)

        def put(x):
            x = jnp.asarray(x)
            spec = P("dp") if (x.ndim >= 1 and x.shape[0] % dp == 0) else P()
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return jax.tree.map(put, batch)

    def update(self, batch: dict, *extra) -> dict:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, self._shard_batch(batch), *extra
        )
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self) -> Any:
        """Host numpy copy for broadcast to CPU env runners."""
        return params_to_numpy(self.params)

    def set_weights(self, params: Any) -> None:
        self.params = jax.tree.map(jnp.asarray, params)
