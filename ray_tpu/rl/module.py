"""RLModule: the policy/value network as a pure-JAX (init, forward) pair.

The reference's RLModule (reference: rllib/core/rl_module/rl_module.py) is
a torch nn.Module with forward_inference/forward_train methods; here the
module is functional — params are an explicit pytree so the same weights
move freely between CPU rollout actors (numpy) and the TPU learner
(sharded jax.Arrays) without framework glue.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = Any  # pytree of arrays


@dataclass(frozen=True)
class RLModule:
    """Base: subclasses define init(key) and forward(params, obs)."""

    observation_size: int
    num_actions: int

    def init(self, key: jax.Array) -> Params:
        raise NotImplementedError

    def forward(self, params: Params, obs: jnp.ndarray) -> dict:
        """obs [B, obs_size] -> {"logits": [B, A], "value": [B]}."""
        raise NotImplementedError


def _dense_init(key, n_in, n_out, scale=None):
    if scale is None:
        scale = float(np.sqrt(2.0 / n_in))
    w = jax.random.normal(key, (n_in, n_out), jnp.float32) * scale
    return {"w": w, "b": jnp.zeros((n_out,), jnp.float32)}


def _dense(p, x):
    return x @ p["w"] + p["b"]


@dataclass(frozen=True)
class MLPModule(RLModule):
    """Shared-trunk MLP with policy and value heads (the reference's default
    fcnet, rllib/core/models/configs.py MLPHeadConfig)."""

    hidden: tuple = (64, 64)
    dueling: bool = False  # DQN dueling heads: value + advantage

    def init(self, key: jax.Array) -> Params:
        keys = jax.random.split(key, len(self.hidden) + 3)
        trunk = []
        n_in = self.observation_size
        for i, h in enumerate(self.hidden):
            trunk.append(_dense_init(keys[i], n_in, h))
            n_in = h
        return {
            "trunk": trunk,
            "policy": _dense_init(keys[-2], n_in, self.num_actions, scale=0.01),
            "value": _dense_init(keys[-1], n_in, 1, scale=1.0),
        }

    def forward(self, params: Params, obs: jnp.ndarray) -> dict:
        x = obs.astype(jnp.float32)
        for layer in params["trunk"]:
            x = jnp.tanh(_dense(layer, x))
        logits = _dense(params["policy"], x)
        value = _dense(params["value"], x)[..., 0]
        if self.dueling:
            # logits are advantages; combine with state value (dueling DQN).
            logits = value[..., None] + logits - logits.mean(-1, keepdims=True)
        return {"logits": logits, "value": value}


def params_to_numpy(params: Params) -> Params:
    """Device → host copy for shipping weights to CPU rollout actors."""
    return jax.tree.map(lambda a: np.asarray(a), params)
