"""Uniform replay buffer for off-policy algorithms (DQN).

Reference: rllib/utils/replay_buffers/replay_buffer.py — ring storage,
uniform sampling. Stored as preallocated numpy arrays so sampling is a
single fancy-index (no per-item Python objects).
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, observation_size: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, observation_size), np.float32)
        self.next_obs = np.zeros((capacity, observation_size), np.float32)
        self.actions = np.zeros((capacity,), np.int64)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, dones, next_obs) -> None:
        """Append flat [B, ...] transition arrays, wrapping at capacity."""
        n = len(actions)
        idx = (self._idx + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.dones[idx] = dones
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
            "next_obs": self.next_obs[idx],
        }
