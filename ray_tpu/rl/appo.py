"""APPO: asynchronous PPO on the IMPALA architecture (reference:
rllib/algorithms/appo/appo.py — IMPALA's async actor-learner loop with
a PPO clipped-surrogate loss, V-trace off-policy correction computed
against a periodically-refreshed TARGET network, and an optional KL
penalty toward that target; loss math in
appo/torch/appo_torch_learner.py).

TPU-native shape: same async rollout consumption as rl/impala.py (the
learner takes whichever runner's rollout lands first), with the whole
V-trace recursion and clipped update in one jit program; the target
network is just a second param pytree carried as an extra loss arg —
no separate actor, no weight copy off-device.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ray_tpu.rl.algorithm import make_adam
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.learner import Learner


def appo_loss(
    params,
    module,
    batch,
    target_params,
    clip_eps,
    gamma,
    rho_clip,
    c_clip,
    vf_coeff,
    ent_coeff,
    kl_coeff,
):
    """Clipped surrogate on V-trace advantages; targets and the KL
    anchor come from the TARGET network (reference: APPOTorchLearner
    compute_loss_for_module — old_target_policy drives v-trace)."""
    T, N = batch["actions"].shape
    obs = batch["obs"].reshape(T * N, -1)
    out = module.forward(params, obs)
    logits = out["logits"].reshape(T, N, -1)
    values = out["value"].reshape(T, N)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None], axis=-1
    )[..., 0]

    # No stop_gradient needed: grads are taken w.r.t. params only and
    # target_params is a separate loss argument.
    tgt = module.forward(target_params, obs)
    tgt_logits = tgt["logits"].reshape(T, N, -1)
    tgt_values = tgt["value"].reshape(T, N)
    tgt_logp_all = jax.nn.log_softmax(tgt_logits)
    tgt_logp = jnp.take_along_axis(
        tgt_logp_all, batch["actions"][..., None], axis=-1
    )[..., 0]

    # V-trace with ratios of the TARGET policy vs the behavior policy
    # (the target changes slowly, so the correction stays stable while
    # the online policy takes several clipped steps against it).
    rhos = jnp.exp(tgt_logp - batch["logp"])
    clipped_rho = jnp.minimum(rhos, rho_clip)
    cs = jnp.minimum(rhos, c_clip)
    last_value = jax.lax.stop_gradient(
        module.forward(target_params, batch["next_obs"])["value"]
    )
    discounts = gamma * (1.0 - batch["dones"])
    next_values = jnp.concatenate(
        [tgt_values[1:], last_value[None]], axis=0
    )
    deltas = clipped_rho * (
        batch["rewards"] + discounts * next_values - tgt_values
    )

    def backward(carry, xs):
        delta, disc, c = xs
        carry = delta + disc * c * carry
        return carry, carry

    _, acc_rev = jax.lax.scan(
        backward,
        jnp.zeros(N),
        (deltas[::-1], discounts[::-1], cs[::-1]),
    )
    vs = tgt_values + acc_rev[::-1]
    vs_next = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = jax.lax.stop_gradient(
        clipped_rho
        * (batch["rewards"] + discounts * vs_next - tgt_values)
    )
    pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

    # PPO clipped surrogate: the ONLINE policy's ratio vs behavior.
    ratio = jnp.exp(logp - batch["logp"])
    surrogate = -jnp.minimum(
        ratio * pg_adv,
        jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * pg_adv,
    ).mean()

    vf_loss = 0.5 * ((jax.lax.stop_gradient(vs) - values) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    # KL(target || online): keeps the online policy from drifting far
    # from the policy that anchors the V-trace targets.
    kl = (
        (jnp.exp(tgt_logp_all) * (tgt_logp_all - logp_all))
        .sum(-1)
        .mean()
    )
    loss = (
        surrogate
        + vf_coeff * vf_loss
        - ent_coeff * entropy
        + kl_coeff * kl
    )
    return loss, {
        "policy_loss": surrogate,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "kl_to_target": kl,
        "mean_rho": rhos.mean(),
        "clip_frac": (jnp.abs(ratio - 1) > clip_eps).mean(),
    }


@dataclass(frozen=True)
class APPOConfig(IMPALAConfig):
    clip_eps: float = 0.3
    kl_coeff: float = 0.1
    # Learner updates between target-network refreshes (reference:
    # target_network_update_freq, counted in env steps there).
    target_update_freq: int = 8

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    """IMPALA's async loop (sample consumption, connector sync,
    runner refresh) with the APPO loss and a target network — the only
    differences ARE the loss and the target refresh, expressed through
    IMPALA's _extra_update_args/_after_update hooks."""

    def __init__(self, config: APPOConfig):
        super().__init__(config)
        self._updates_since_target = 0
        self.target_params = jax.tree.map(
            jnp.asarray, self.learner.params
        )

    def _make_learner(self) -> Learner:
        cfg = self.config

        def loss(params, module, batch, target_params):
            return appo_loss(
                params, module, batch, target_params, cfg.clip_eps,
                cfg.gamma, cfg.rho_clip, cfg.c_clip, cfg.vf_coeff,
                cfg.ent_coeff, cfg.kl_coeff,
            )

        return Learner(
            self.module, loss, make_adam(cfg.lr), mesh=cfg.mesh,
            seed=cfg.seed,
        )

    def _extra_update_args(self) -> tuple:
        return (self.target_params,)

    def _after_update(self) -> None:
        self._updates_since_target += 1
        if self._updates_since_target >= self.config.target_update_freq:
            self.target_params = jax.tree.map(
                jnp.asarray, self.learner.params
            )
            self._updates_since_target = 0
