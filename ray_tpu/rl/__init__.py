"""TPU-native RL library (RLlib equivalent).

Architecture mirrors the reference's split (reference:
rllib/algorithms/algorithm.py:212, rllib/env/env_runner_group.py:70,
rllib/core/learner/learner_group.py:101, rllib/core/rl_module/):

- :class:`RLModule` — the neural net, a pure-JAX (init, forward) pair.
- :class:`EnvRunnerGroup` — CPU rollout actors stepping vectorized envs.
- :class:`Learner`/`LearnerGroup` — one pjit'd update program over the
  device mesh (data-parallel across chips) instead of the reference's
  DDP-across-learner-actors.
- :class:`Algorithm` — the driver loop: sample → learn → broadcast.
"""

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.appo import APPO, APPOConfig
from ray_tpu.rl.bc import BC, BCConfig
from ray_tpu.rl.connectors import (
    CastObs,
    ClipObs,
    ClipReward,
    Connector,
    ConnectorPipeline,
    MeanStdObsFilter,
)
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.env import CartPole, Env, make_env, register_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.module import MLPModule, RLModule
from ray_tpu.rl.ppo import PPO, PPOConfig
from ray_tpu.rl.replay import ReplayBuffer
from ray_tpu.rl.sac import SAC, SACConfig

__all__ = [
    "APPO",
    "APPOConfig",
    "Algorithm",
    "AlgorithmConfig",
    "BC",
    "BCConfig",
    "CartPole",
    "CastObs",
    "ClipObs",
    "ClipReward",
    "Connector",
    "ConnectorPipeline",
    "MeanStdObsFilter",
    "DQN",
    "DQNConfig",
    "Env",
    "EnvRunnerGroup",
    "IMPALA",
    "IMPALAConfig",
    "Learner",
    "MLPModule",
    "PPO",
    "PPOConfig",
    "RLModule",
    "ReplayBuffer",
    "SAC",
    "SACConfig",
    "make_env",
    "register_env",
]


def __getattr__(name):
    if name == "Learner":
        from ray_tpu.rl.learner import Learner

        return Learner
    raise AttributeError(f"module 'ray_tpu.rl' has no attribute {name!r}")
