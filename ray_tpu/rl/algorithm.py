"""Algorithm: the driver-side train loop (sample → learn → broadcast).

Reference: rllib/algorithms/algorithm.py:212 (`step` :1191 /
`training_step` :2301) — config object builds the algorithm, `train()`
runs one iteration and returns a metrics dict, checkpoints via
save/restore. Here the learner is a mesh-sharded jit program in the driver
process (the TPU owner) and sampling fans out over EnvRunner actors.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass, field, replace
from typing import Any

import numpy as np
import optax

from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.module import MLPModule, RLModule


@dataclass(frozen=True)
class AlgorithmConfig:
    """Builder-style config (reference: AlgorithmConfig.environment()/
    .env_runners()/.training() chains; here a frozen dataclass with
    replace())."""

    env: str = "CartPole"
    env_kwargs: dict = field(default_factory=dict)
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_len: int = 64
    lr: float = 3e-4
    gamma: float = 0.99
    hidden: tuple = (64, 64)
    seed: int = 0
    mesh: Any = None  # jax.sharding.Mesh with a 'dp' axis, or None
    # env-to-module ConnectorPipeline (rl/connectors.py); every runner
    # gets a copy, running stats sync through the group.
    connectors: Any = None

    def copy(self, **kwargs) -> "AlgorithmConfig":
        return replace(self, **kwargs)

    def build(self) -> "Algorithm":
        raise NotImplementedError


class Algorithm:
    """Base: holds module, learner, runner group; subclass implements
    training_step()."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        probe = make_env(config.env, **config.env_kwargs)
        self.module = self._make_module(probe)
        self.learner = self._make_learner()
        self.runners = EnvRunnerGroup(
            config.env,
            self.module,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            rollout_len=config.rollout_len,
            env_kwargs=config.env_kwargs,
            seed=config.seed,
            connectors=config.connectors,
        )
        self.runners.set_weights(self.learner.get_weights())
        self.iteration = 0
        self._return_window: list[float] = []

    # -- subclass hooks ----------------------------------------------------
    def _make_module(self, probe_env) -> RLModule:
        return MLPModule(
            observation_size=probe_env.observation_size,
            num_actions=probe_env.num_actions,
            hidden=self.config.hidden,
        )

    def _make_learner(self):
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    # -- public API --------------------------------------------------------
    def train(self) -> dict:
        metrics = self.training_step()
        self.iteration += 1
        window = self._return_window[-100:]
        metrics.update(
            training_iteration=self.iteration,
            episode_return_mean=float(np.mean(window)) if window else float("nan"),
            episodes_total=len(self._return_window),
        )
        return metrics

    def _record_episodes(self, samples: list[dict]) -> None:
        for s in samples:
            self._return_window.extend(s["episode_returns"])

    def stop(self) -> None:
        """Kill rollout actors and release their resources (reference:
        Algorithm.stop / EnvRunnerGroup.stop)."""
        import ray_tpu

        for r in self.runners.runners:
            try:
                ray_tpu.kill(r)
            # tpulint: allow(broad-except reason=stop() kills best-effort; a runner that already died is exactly the state stop wants)
            except Exception:
                pass

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        connectors = self.runners.connectors
        with open(os.path.join(path, "algorithm.pkl"), "wb") as f:
            pickle.dump(
                {
                    "weights": self.learner.get_weights(),
                    "iteration": self.iteration,
                    "config": self.config,
                    # Filter statistics are part of the policy: a net
                    # trained on normalized obs is garbage without them.
                    "connector_state": (
                        connectors.get_state() if connectors else None
                    ),
                },
                f,
            )
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algorithm.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.set_weights(state["weights"])
        self.iteration = state["iteration"]
        self.runners.set_weights(self.learner.get_weights())
        cstate = state.get("connector_state")
        if cstate and self.runners.connectors is not None:
            self.runners.broadcast_connector_state(cstate)

    def get_policy_weights(self) -> Any:
        return self.learner.get_weights()

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        """Greedy action for a batch of observations (serving path).
        Observations run through the SAME connector pipeline the policy
        trained on (stats frozen — serving must not mutate them)."""
        import jax.numpy as jnp

        if self.runners.connectors is not None:
            obs = self.runners.connectors(
                {"obs": np.asarray(obs)},
                {"phase": "step", "update_stats": False},
            )["obs"]
        out = self.module.forward(self.learner.params, jnp.asarray(obs))
        return np.asarray(out["logits"].argmax(-1))


def make_adam(lr: float, grad_clip: float = 0.5) -> optax.GradientTransformation:
    return optax.chain(optax.clip_by_global_norm(grad_clip), optax.adam(lr))
