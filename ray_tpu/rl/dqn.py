"""DQN: double Q-learning with target network and uniform replay
(reference: rllib/algorithms/dqn/dqn.py, default_dqn_rl_module.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, make_adam
from ray_tpu.rl.env import make_env
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.module import MLPModule
from ray_tpu.rl.replay import ReplayBuffer


def dqn_loss(params, module, batch, target_params, gamma):
    q = module.forward(params, batch["obs"])["logits"]
    q_taken = jnp.take_along_axis(q, batch["actions"][:, None], -1)[:, 0]
    # Double DQN: online net picks the argmax, target net evaluates it.
    next_q_online = module.forward(params, batch["next_obs"])["logits"]
    next_act = next_q_online.argmax(-1)
    next_q_target = module.forward(target_params, batch["next_obs"])["logits"]
    next_q = jnp.take_along_axis(next_q_target, next_act[:, None], -1)[:, 0]
    target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * jax.lax.stop_gradient(
        next_q
    )
    td = q_taken - target
    loss = jnp.where(jnp.abs(td) < 1.0, 0.5 * td**2, jnp.abs(td) - 0.5).mean()
    return loss, {"td_error_mean": jnp.abs(td).mean(), "q_mean": q_taken.mean()}


@dataclass(frozen=True)
class DQNConfig(AlgorithmConfig):
    lr: float = 1e-3
    buffer_capacity: int = 50_000
    train_batch_size: int = 128
    num_updates_per_iter: int = 16
    target_update_interval: int = 4  # iterations between target syncs
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 50
    learning_starts: int = 500  # min transitions before updates begin

    def build(self) -> "DQN":
        return DQN(self)


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        super().__init__(config)
        probe = make_env(config.env, **config.env_kwargs)
        self.buffer = ReplayBuffer(
            config.buffer_capacity, probe.observation_size, seed=config.seed
        )
        self.target_params = self.learner.params

    def _make_module(self, probe_env):
        return MLPModule(
            observation_size=probe_env.observation_size,
            num_actions=probe_env.num_actions,
            hidden=self.config.hidden,
            dueling=True,
        )

    def _make_learner(self) -> Learner:
        gamma = self.config.gamma

        def loss(params, module, batch, target_params):
            return dqn_loss(params, module, batch, target_params, gamma)

        return Learner(
            self.module, loss, make_adam(self.config.lr, grad_clip=10.0),
            mesh=self.config.mesh, seed=self.config.seed,
        )

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample(epsilon=self._epsilon())
        self._record_episodes(samples)
        for s in samples:
            T, N, D = s["obs"].shape
            # next_obs within the rollout is obs shifted by one step; the
            # final step's successor is the runner's current obs. Resets
            # inside the rollout are fine: dones masks the bootstrap.
            next_obs = np.concatenate([s["obs"][1:], s["next_obs"][None]], 0)
            self.buffer.add_batch(
                s["obs"].reshape(-1, D),
                s["actions"].reshape(-1),
                s["rewards"].reshape(-1),
                s["dones"].reshape(-1),
                next_obs.reshape(-1, D),
            )

        metrics: dict = {"epsilon": self._epsilon(), "buffer_size": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                metrics.update(self.learner.update(batch, self.target_params))
            if self.iteration % cfg.target_update_interval == 0:
                self.target_params = self.learner.params
            self.runners.set_weights(self.learner.get_weights())
        return metrics
