"""Connector pipelines: composable observation/batch transforms shared
across algorithms (reference: rllib/connectors/ — ConnectorV2 and
ConnectorPipelineV2, the reference's mechanism for reusing obs
preprocessing between algorithms; env_runner applies the env-to-module
pipeline each step, learners apply a batch pipeline before the update).

A connector is ``__call__(batch: dict, ctx: dict) -> dict`` plus
optional state (running statistics). Two phases:

- ``"step"``: applied inside the EnvRunner to ``{"obs": [N, D]}``
  before each forward pass — the transformed obs is ALSO what lands in
  the rollout buffer, so the learner trains on exactly the view the
  policy acted on.
- ``"batch"``: applied once to the completed rollout sample (reward
  clipping and friends).

Stateful connectors (``MeanStdObsFilter``) ship DELTAS — statistics
accumulated since their last report, cleared on reporting — back with
each sample; the driver absorbs every runner's deltas into one global
state and rebroadcasts it. Delta shipping is what makes the pooling
correct: absolute states share broadcast history, and pooling them
would re-count that history once per runner per round (the reference's
FilterManager.synchronize_filters clears filter buffers after each
report for exactly this reason, rllib/utils/filter_manager.py).
"""

from __future__ import annotations

import numpy as np


class Connector:
    phase = "step"  # "step" | "batch" | "both"

    @property
    def name(self) -> str:
        return type(self).__name__

    def __call__(self, batch: dict, ctx: dict) -> dict:
        raise NotImplementedError

    # -- optional running state (synced across runners) ----------------
    def get_state(self) -> dict:
        """Full-state snapshot (broadcast + checkpoints)."""
        return {}

    def set_state(self, state: dict) -> None:
        pass

    def report_delta(self) -> dict:
        """Runner side: statistics accumulated since the last report;
        CLEARS the delta buffer (empty dict = nothing to report)."""
        return {}

    def absorb_delta(self, delta: dict) -> None:
        """Driver side: fold one runner's reported delta into this
        (global) connector's state."""


class ConnectorPipeline(Connector):
    """Ordered connectors with the reference's mutation surface
    (append/prepend/insert_before/insert_after/remove)."""

    phase = "both"

    def __init__(self, *connectors: Connector):
        self.connectors = list(connectors)

    def __call__(self, batch: dict, ctx: dict) -> dict:
        phase = ctx.get("phase", "step")
        for c in self.connectors:
            if c.phase in (phase, "both"):
                batch = c(batch, ctx)
        return batch

    def _index_of(self, name: str) -> int:
        for i, c in enumerate(self.connectors):
            if c.name == name:
                return i
        raise KeyError(f"no connector named {name!r}")

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def insert_before(self, name: str, connector: Connector):
        self.connectors.insert(self._index_of(name), connector)
        return self

    def insert_after(self, name: str, connector: Connector):
        self.connectors.insert(self._index_of(name) + 1, connector)
        return self

    def remove(self, name: str) -> "ConnectorPipeline":
        self.connectors.pop(self._index_of(name))
        return self

    def _state_keys(self) -> "list[tuple[str, Connector]]":
        """(key, connector) pairs with keys unique per INSTANCE: the
        first occurrence of a class keeps its bare name, later ones get
        ``Name_1``, ``Name_2``… in pipeline order. Two ClipObs with
        different bounds therefore sync independently instead of one
        silently overwriting the other — valid as long as runner and
        driver pipelines are composed identically, which filter sync
        already requires."""
        seen: dict[str, int] = {}
        out = []
        for c in self.connectors:
            n = seen.get(c.name, 0)
            seen[c.name] = n + 1
            out.append((c.name if n == 0 else f"{c.name}_{n}", c))
        return out

    def get_state(self) -> dict:
        return {
            k: s for k, c in self._state_keys() if (s := c.get_state())
        }

    def set_state(self, state: dict) -> None:
        for k, c in self._state_keys():
            if k in state:
                c.set_state(state[k])

    def report_delta(self) -> dict:
        return {
            k: d for k, c in self._state_keys() if (d := c.report_delta())
        }

    def absorb_deltas(self, deltas: list[dict]) -> None:
        """Fold per-runner delta reports into this (driver) pipeline's
        global state, connector by connector."""
        for k, c in self._state_keys():
            for report in deltas:
                if k in report:
                    c.absorb_delta(report[k])


# ------------------------------------------------------------- builtins


class CastObs(Connector):
    def __init__(self, dtype=np.float32):
        self.dtype = np.dtype(dtype)

    def __call__(self, batch, ctx):
        batch["obs"] = np.asarray(batch["obs"], dtype=self.dtype)
        return batch


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, batch, ctx):
        batch["obs"] = np.clip(batch["obs"], self.low, self.high)
        return batch


class ClipReward(Connector):
    """Batch-phase reward clipping (reference: ClipRewards connector)."""

    phase = "batch"

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, batch, ctx):
        if "rewards" in batch:
            batch["rewards"] = np.clip(batch["rewards"], self.low, self.high)
        return batch


def _pool_moments(
    count_a, mean_a, m2_a, count_b, mean_b, m2_b
) -> tuple:
    """Chan et al. parallel pooling of two disjoint moment sets."""
    if count_b == 0:
        return count_a, mean_a, m2_a
    if count_a == 0:
        return count_b, mean_b.copy(), m2_b.copy()
    total = count_a + count_b
    d = mean_b - mean_a
    mean = mean_a + d * (count_b / total)
    m2 = m2_a + m2_b + d * d * (count_a * count_b / total)
    return total, mean, m2


class MeanStdObsFilter(Connector):
    """Running-mean/std observation normalization (reference:
    MeanStdFilter, rllib/connectors/env_to_module/mean_std_filter.py).

    Two moment sets: the WORKING stats (global broadcast + local
    unreported observations — what normalization uses) and the DELTA
    buffer (local observations since the last report). ``report_delta``
    ships and clears the buffer; the driver absorbs deltas from every
    runner into its own working stats and rebroadcasts, so each
    observation is pooled exactly once globally.
    """

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self.count = 0.0
        self.mean: np.ndarray | None = None
        self.m2: np.ndarray | None = None
        self._d_count = 0.0
        self._d_mean: np.ndarray | None = None
        self._d_m2: np.ndarray | None = None

    def _ensure(self, dim: int):
        if self.mean is None:
            self.mean = np.zeros(dim)
            self.m2 = np.zeros(dim)
        if self._d_mean is None:
            self._d_mean = np.zeros(dim)
            self._d_m2 = np.zeros(dim)

    def __call__(self, batch, ctx):
        obs = np.asarray(batch["obs"], dtype=np.float64)
        self._ensure(obs.shape[-1])
        if ctx.get("update_stats", True):
            flat = obs.reshape(-1, obs.shape[-1])
            bcount = float(len(flat))
            bmean = flat.mean(0)
            bm2 = ((flat - bmean) ** 2).sum(0)
            self.count, self.mean, self.m2 = _pool_moments(
                self.count, self.mean, self.m2, bcount, bmean, bm2
            )
            self._d_count, self._d_mean, self._d_m2 = _pool_moments(
                self._d_count, self._d_mean, self._d_m2,
                bcount, bmean, bm2,
            )
        std = np.sqrt(self.m2 / max(1.0, self.count - 1)) + self.eps
        out = np.clip((obs - self.mean) / std, -self.clip, self.clip)
        batch["obs"] = out.astype(np.float32)
        return batch

    def get_state(self) -> dict:
        return {
            "count": self.count,
            "mean": None if self.mean is None else self.mean.copy(),
            "m2": None if self.m2 is None else self.m2.copy(),
        }

    def set_state(self, state: dict) -> None:
        # Working stats only: the delta buffer keeps accumulating so
        # nothing reported later is lost to the broadcast overwrite.
        self.count = state["count"]
        self.mean = None if state["mean"] is None else state["mean"].copy()
        self.m2 = None if state["m2"] is None else state["m2"].copy()

    def report_delta(self) -> dict:
        if self._d_count == 0:
            return {}
        delta = {
            "count": self._d_count,
            "mean": self._d_mean.copy(),
            "m2": self._d_m2.copy(),
        }
        self._d_count = 0.0
        self._d_mean = np.zeros_like(self._d_mean)
        self._d_m2 = np.zeros_like(self._d_m2)
        return delta

    def absorb_delta(self, delta: dict) -> None:
        if not delta or delta["count"] == 0:
            return
        self._ensure(len(delta["mean"]))
        self.count, self.mean, self.m2 = _pool_moments(
            self.count, self.mean, self.m2,
            delta["count"], delta["mean"], delta["m2"],
        )
