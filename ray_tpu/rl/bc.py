"""BC: offline behavior cloning (reference: rllib/algorithms/bc/bc.py —
supervised imitation of a recorded dataset, the simplest offline-RL
algorithm and the reference's offline-data smoke test).

The dataset is host numpy ({"obs": [N, D], "actions": [N]}); each
training_step runs jit'd cross-entropy minibatches. Env runners are kept
only for periodic evaluation of the cloned policy.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, make_adam
from ray_tpu.rl.learner import Learner


def bc_loss(params, module, batch):
    out = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None], axis=-1
    )[:, 0]
    loss = -logp.mean()
    acc = (out["logits"].argmax(-1) == batch["actions"]).mean()
    return loss, {"bc_loss": loss, "accuracy": acc}


@dataclass(frozen=True)
class BCConfig(AlgorithmConfig):
    dataset: dict = field(default_factory=dict)  # {"obs", "actions"}
    batch_size: int = 256
    updates_per_step: int = 16
    evaluate_every: int = 5  # iterations between env evaluations

    def build(self) -> "BC":
        return BC(self)


class BC(Algorithm):
    def __init__(self, config: BCConfig):
        if not config.dataset:
            raise ValueError("BCConfig.dataset must hold 'obs'/'actions'")
        super().__init__(config)
        self._obs = np.asarray(config.dataset["obs"], np.float32)
        self._actions = np.asarray(config.dataset["actions"], np.int64)
        self._rng = np.random.default_rng(config.seed)

    def _make_learner(self) -> Learner:
        return Learner(
            self.module, bc_loss, make_adam(self.config.lr),
            mesh=self.config.mesh, seed=self.config.seed,
        )

    def training_step(self) -> dict:
        cfg = self.config
        n = len(self._obs)
        metrics: dict = {}
        for _ in range(cfg.updates_per_step):
            idx = self._rng.integers(0, n, min(cfg.batch_size, n))
            metrics = self.learner.update(
                {"obs": self._obs[idx], "actions": self._actions[idx]}
            )
        metrics["num_env_steps_sampled"] = 0
        # Offline training: evaluate the cloned policy in the env only
        # periodically (rollouts are for reporting, not learning).
        if (self.iteration + 1) % cfg.evaluate_every == 0:
            self.runners.set_weights(self.learner.get_weights())
            samples = self.runners.sample()
            self._record_episodes(samples)
        return metrics
