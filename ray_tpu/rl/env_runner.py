"""EnvRunner actors: CPU rollout workers sampling trajectories.

Mirrors the reference's EnvRunnerGroup of remote workers (reference:
rllib/env/env_runner_group.py:70, single_agent_env_runner.py): each runner
actor holds a vector of envs plus a CPU copy of the module params, samples
fixed-length rollouts, and returns flat numpy batches. Inference inside the
runner is jitted on the CPU backend — rollouts never touch the TPU, which
stays dedicated to the learner (SURVEY.md §7 stage 8: "TPU learner group +
CPU rollout env runners").
"""

from __future__ import annotations

from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rl.env import make_env
from ray_tpu.rl.module import RLModule


class EnvRunner:
    """Steps `num_envs` env copies for `rollout_len` steps per sample() call."""

    def __init__(
        self,
        env_name: str,
        env_kwargs: dict,
        module: RLModule,
        num_envs: int,
        rollout_len: int,
        seed: int,
    ):
        import jax

        self._jax = jax
        self.module = module
        self.rollout_len = rollout_len
        self.envs = [make_env(env_name, **env_kwargs) for _ in range(num_envs)]
        self.obs = np.stack([e.reset(seed + i) for i, e in enumerate(self.envs)])
        self.params = None
        self._rng = np.random.default_rng(seed)
        self._episode_returns = np.zeros(num_envs)
        self._completed: list[float] = []
        self._fwd = jax.jit(module.forward, backend="cpu")

    def set_weights(self, params: Any) -> None:
        self.params = params

    def sample(self, epsilon: float = 0.0) -> dict:
        """Collect [T, N, ...] batches; also returns logp/value for PPO."""
        T, N = self.rollout_len, len(self.envs)
        obs_buf = np.zeros((T, N, self.envs[0].observation_size), np.float32)
        act_buf = np.zeros((T, N), np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            out = self._fwd(self.params, self.obs)
            logits = np.asarray(out["logits"])
            values = np.asarray(out["value"])
            # Sample from the categorical policy (Gumbel trick), with
            # optional epsilon-greedy override for DQN-style exploration.
            noise = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + noise, axis=-1)
            if epsilon > 0.0:
                randomize = self._rng.random(N) < epsilon
                actions = np.where(
                    randomize,
                    self._rng.integers(0, self.envs[0].num_actions, N),
                    actions,
                )
            logp = logits - _logsumexp(logits)
            obs_buf[t] = self.obs
            act_buf[t] = actions
            val_buf[t] = values
            logp_buf[t] = logp[np.arange(N), actions]
            for i, env in enumerate(self.envs):
                nobs, r, done = env.step(int(actions[i]))
                rew_buf[t, i] = r
                done_buf[t, i] = float(done)
                self._episode_returns[i] += r
                if done:
                    self._completed.append(self._episode_returns[i])
                    self._episode_returns[i] = 0.0
                    nobs = env.reset()
                self.obs[i] = nobs

        # Bootstrap value for the state after the last step (PPO GAE).
        last_val = np.asarray(self._fwd(self.params, self.obs)["value"])
        completed, self._completed = self._completed, []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "logp": logp_buf,
            "values": val_buf,
            "last_value": last_val,
            "next_obs": self.obs.copy(),
            "episode_returns": completed,
        }


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(-1, keepdims=True))


class EnvRunnerGroup:
    """Fan-out over EnvRunner actors (reference: EnvRunnerGroup.foreach_worker)."""

    def __init__(
        self,
        env_name: str,
        module: RLModule,
        *,
        num_runners: int = 2,
        num_envs_per_runner: int = 4,
        rollout_len: int = 64,
        env_kwargs: dict | None = None,
        seed: int = 0,
    ):
        runner_cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            runner_cls.remote(
                env_name,
                env_kwargs or {},
                module,
                num_envs_per_runner,
                rollout_len,
                seed + 1000 * i,
            )
            for i in range(num_runners)
        ]

    def set_weights(self, params) -> None:
        ray_tpu.get([r.set_weights.remote(params) for r in self.runners])

    def sample(self, epsilon: float = 0.0) -> list[dict]:
        return ray_tpu.get([r.sample.remote(epsilon) for r in self.runners])
