"""EnvRunner actors: CPU rollout workers sampling trajectories.

Mirrors the reference's EnvRunnerGroup of remote workers (reference:
rllib/env/env_runner_group.py:70, single_agent_env_runner.py): each runner
actor holds a vector of envs plus a CPU copy of the module params, samples
fixed-length rollouts, and returns flat numpy batches. Inference inside the
runner is jitted on the CPU backend — rollouts never touch the TPU, which
stays dedicated to the learner (SURVEY.md §7 stage 8: "TPU learner group +
CPU rollout env runners").
"""

from __future__ import annotations

from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rl.env import make_env
from ray_tpu.rl.module import RLModule


class EnvRunner:
    """Steps `num_envs` env copies for `rollout_len` steps per sample() call."""

    def __init__(
        self,
        env_name: str,
        env_kwargs: dict,
        module: RLModule,
        num_envs: int,
        rollout_len: int,
        seed: int,
        connectors=None,
    ):
        import jax

        self._jax = jax
        self.module = module
        self.rollout_len = rollout_len
        self.envs = [make_env(env_name, **env_kwargs) for _ in range(num_envs)]
        self.obs = np.stack([e.reset(seed + i) for i, e in enumerate(self.envs)])
        self.params = None
        self._rng = np.random.default_rng(seed)
        self._episode_returns = np.zeros(num_envs)
        self._completed: list[float] = []
        self._fwd = jax.jit(module.forward, backend="cpu")
        # env-to-module connector pipeline (reference: connectors/
        # env_to_module) — each runner actor owns its copy; running
        # stats are merged by the group after sampling.
        self.connectors = connectors

    def set_weights(self, params: Any) -> None:
        self.params = params

    def get_connector_state(self) -> dict:
        return self.connectors.get_state() if self.connectors else {}

    def set_connector_state(self, state: dict) -> None:
        if self.connectors:
            self.connectors.set_state(state)

    def _module_obs(self, obs: np.ndarray, update_stats: bool = True):
        """Run the env-to-module pipeline; the transformed view is both
        what the policy sees and what lands in the rollout buffer."""
        if self.connectors is None:
            return obs
        out = self.connectors(
            {"obs": obs.copy()},
            {"phase": "step", "update_stats": update_stats},
        )
        return out["obs"]

    def sample(self, epsilon: float = 0.0) -> dict:
        """Collect [T, N, ...] batches; also returns logp/value for PPO."""
        T, N = self.rollout_len, len(self.envs)
        obs_buf = np.zeros((T, N, self.envs[0].observation_size), np.float32)
        act_buf = np.zeros((T, N), np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            mobs = self._module_obs(self.obs)
            out = self._fwd(self.params, mobs)
            logits = np.asarray(out["logits"])
            values = np.asarray(out["value"])
            # Sample from the categorical policy (Gumbel trick), with
            # optional epsilon-greedy override for DQN-style exploration.
            noise = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + noise, axis=-1)
            if epsilon > 0.0:
                randomize = self._rng.random(N) < epsilon
                actions = np.where(
                    randomize,
                    self._rng.integers(0, self.envs[0].num_actions, N),
                    actions,
                )
            logp = logits - _logsumexp(logits)
            obs_buf[t] = mobs
            act_buf[t] = actions
            val_buf[t] = values
            logp_buf[t] = logp[np.arange(N), actions]
            for i, env in enumerate(self.envs):
                nobs, r, done = env.step(int(actions[i]))
                rew_buf[t, i] = r
                done_buf[t, i] = float(done)
                self._episode_returns[i] += r
                if done:
                    self._completed.append(self._episode_returns[i])
                    self._episode_returns[i] = 0.0
                    nobs = env.reset()
                self.obs[i] = nobs

        # Bootstrap value for the state after the last step (PPO GAE).
        # update_stats=False: this obs is re-transformed (and counted)
        # at the start of the next sample().
        next_mobs = self._module_obs(self.obs, update_stats=False)
        last_val = np.asarray(self._fwd(self.params, next_mobs)["value"])
        completed, self._completed = self._completed, []
        sample = {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "logp": logp_buf,
            "values": val_buf,
            "last_value": last_val,
            "next_obs": next_mobs.copy(),
            "episode_returns": completed,
        }
        if self.connectors is not None:
            sample = self.connectors(sample, {"phase": "batch"})
            # Deltas only (cleared on report): the group absorbs them
            # into the global state and rebroadcasts — absolute states
            # would re-count shared history once per runner per sync.
            sample["connector_state"] = self.connectors.report_delta()
        return sample


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(-1, keepdims=True))


class EnvRunnerGroup:
    """Fan-out over EnvRunner actors (reference: EnvRunnerGroup.foreach_worker)."""

    def __init__(
        self,
        env_name: str,
        module: RLModule,
        *,
        num_runners: int = 2,
        num_envs_per_runner: int = 4,
        rollout_len: int = 64,
        env_kwargs: dict | None = None,
        seed: int = 0,
        connectors=None,
    ):
        # Driver-side pipeline copy: used to merge the per-runner
        # running stats (reference: FilterManager.synchronize_filters).
        self.connectors = connectors
        runner_cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            runner_cls.remote(
                env_name,
                env_kwargs or {},
                module,
                num_envs_per_runner,
                rollout_len,
                seed + 1000 * i,
                connectors,
            )
            for i in range(num_runners)
        ]

    def set_weights(self, params) -> None:
        ray_tpu.get([r.set_weights.remote(params) for r in self.runners])

    def sample(self, epsilon: float = 0.0) -> list[dict]:
        samples = ray_tpu.get(
            [r.sample.remote(epsilon) for r in self.runners]
        )
        self.sync_connectors(
            [s.get("connector_state", {}) for s in samples]
        )
        return samples

    def sync_connectors(
        self, deltas: list[dict], blocking: bool = True
    ) -> None:
        """Absorb per-runner delta reports into the driver's global
        pipeline state and rebroadcast it, so every runner normalizes
        with the same view and every observation is pooled exactly
        once. Async algorithms pass ``blocking=False``: actor calls
        execute in order, so awaiting the broadcast would barrier on
        every runner's in-flight rollout."""
        if self.connectors is None:
            return
        deltas = [d for d in deltas if d]
        if not deltas:
            return
        self.connectors.absorb_deltas(deltas)
        self.broadcast_connector_state(
            self.connectors.get_state(), blocking=blocking
        )

    def broadcast_connector_state(
        self, state: dict, blocking: bool = True
    ) -> None:
        """Push a full pipeline state to every runner (sync tail +
        checkpoint restore share this fanout)."""
        if self.connectors is not None:
            self.connectors.set_state(state)
        refs = [
            r.set_connector_state.remote(state) for r in self.runners
        ]
        if blocking:
            ray_tpu.get(refs)
