"""IMPALA: asynchronous actor-learner with V-trace off-policy correction
(reference: rllib/algorithms/impala/impala.py + the vtrace math in
rllib/algorithms/impala/vtrace_*.py; Espeholt et al. 2018).

TPU-native shape: rollouts arrive asynchronously from CPU env-runner
actors (each keeps sampling with slightly stale weights — the point of
IMPALA); the learner consumes whichever rollout finishes first and the
whole V-trace recursion runs inside one jit program via lax.scan instead
of a host loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, make_adam
from ray_tpu.rl.learner import Learner


def vtrace_loss(
    params, module, batch, gamma, rho_clip, c_clip, vf_coeff, ent_coeff
):
    """V-trace targets + policy gradient on [T, N] rollouts."""
    T, N = batch["actions"].shape
    obs = batch["obs"].reshape(T * N, -1)
    out = module.forward(params, obs)
    logits = out["logits"].reshape(T, N, -1)
    values = out["value"].reshape(T, N)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None], axis=-1
    )[..., 0]

    # Importance ratios vs the BEHAVIOR policy that sampled the rollout.
    rhos = jnp.exp(logp - batch["logp"])
    clipped_rho = jnp.minimum(rhos, rho_clip)
    cs = jnp.minimum(rhos, c_clip)

    # Bootstrap from the CURRENT critic at the rollout's next_obs — the
    # runner's own value estimate is as stale as its policy, and
    # V-trace's correction assumes V comes from the learner's critic.
    last_value = jax.lax.stop_gradient(
        module.forward(params, batch["next_obs"])["value"]
    )
    discounts = gamma * (1.0 - batch["dones"])
    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    deltas = clipped_rho * (
        batch["rewards"] + discounts * next_values - values
    )

    # vs_t - V_t = delta_t + discount_t * c_t * (vs_{t+1} - V_{t+1}),
    # computed as a backward scan (jit-friendly, no host loop).
    def backward(carry, xs):
        delta, disc, c = xs
        carry = delta + disc * c * carry
        return carry, carry

    _, acc_rev = jax.lax.scan(
        backward,
        jnp.zeros(N),
        (deltas[::-1], discounts[::-1], cs[::-1]),
    )
    vs = values + acc_rev[::-1]

    vs_next = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = jax.lax.stop_gradient(
        clipped_rho * (batch["rewards"] + discounts * vs_next - values)
    )
    # Normalize advantages per batch (smooths the sparse-reward, small-
    # batch regime; the reference's IMPALA exposes the same switch as
    # _separate_vf_optimizer-era configs do for PPO).
    pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
    pg_loss = -(pg_adv * logp).mean()
    vf_loss = 0.5 * ((jax.lax.stop_gradient(vs) - values) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return loss, {
        "policy_loss": pg_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_rho": rhos.mean(),
    }


@dataclass(frozen=True)
class IMPALAConfig(AlgorithmConfig):
    rho_clip: float = 1.0
    c_clip: float = 1.0
    vf_coeff: float = 0.5
    ent_coeff: float = 0.02
    # Extra passes over each rollout: later passes are off-policy w.r.t.
    # the updated params, which is exactly what the rho/c clipping
    # corrects — buys faster value-function warm-up per sample.
    updates_per_rollout: int = 4

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA(Algorithm):
    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        # ref → runner handle for in-flight async sample requests.
        self._inflight: dict = {}

    def _make_learner(self) -> Learner:
        cfg = self.config

        def loss(params, module, batch):
            return vtrace_loss(
                params, module, batch, cfg.gamma, cfg.rho_clip,
                cfg.c_clip, cfg.vf_coeff, cfg.ent_coeff,
            )

        return Learner(
            self.module, loss, make_adam(cfg.lr), mesh=cfg.mesh,
            seed=cfg.seed,
        )

    # -- subclass hooks (APPO rides this loop; rl/appo.py) ---------------
    def _extra_update_args(self) -> tuple:
        """Extra positional args for the learner's loss (APPO: the
        target-network params)."""
        return ()

    def _after_update(self) -> None:
        """Called after each learner update (APPO: target refresh)."""

    def training_step(self) -> dict:
        # Keep one sample request outstanding per runner; consume the
        # FIRST one to finish (async actor-learner — other runners keep
        # sampling with whatever weights they last saw; V-trace corrects
        # the policy lag).
        if not self._inflight:
            self._inflight = {
                r.sample.remote(): r for r in self.runners.runners
            }
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=120
        )
        if not ready:
            raise TimeoutError(
                f"{type(self).__name__}: no env-runner rollout completed "
                f"within 120s ({len(self._inflight)} outstanding) — envs "
                "hung or cluster overloaded"
            )
        ref = ready[0]
        runner = self._inflight.pop(ref)
        s = ray_tpu.get(ref)
        self._record_episodes([s])
        if s.get("connector_state"):
            # Absorb this runner's filter deltas; non-blocking — the
            # other runners' set_connector_state calls queue behind
            # their in-flight rollouts, and awaiting them here would
            # turn the async loop into a barrier.
            self.runners.sync_connectors(
                [s["connector_state"]], blocking=False
            )

        batch = {
            "obs": s["obs"],
            "actions": s["actions"],
            "rewards": s["rewards"],
            "dones": s["dones"],
            "logp": s["logp"],
            "next_obs": s["next_obs"],
        }
        for _ in range(max(1, self.config.updates_per_rollout)):
            metrics = self.learner.update(
                batch, *self._extra_update_args()
            )
            self._after_update()
        # Refresh only the runner that just reported, then put it back
        # to work; the rest run behind by design.
        runner.set_weights.remote(self.learner.get_weights())
        self._inflight[runner.sample.remote()] = runner
        metrics["num_env_steps_sampled"] = int(s["rewards"].size)
        return metrics

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
