"""PPO: clipped surrogate objective + GAE (reference:
rllib/algorithms/ppo/ppo.py, torch policy loss in
rllib/algorithms/ppo/torch/ppo_torch_learner.py).

GAE runs on host numpy over the [T, N] rollout (a sequential scan that is
cheap and awkward under jit); the minibatch update is one jit program on
the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, make_adam
from ray_tpu.rl.learner import Learner


def ppo_loss(params, module, batch, clip_eps, vf_coeff, ent_coeff):
    out = module.forward(params, batch["obs"])
    logits = out["logits"]
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None], axis=-1
    )[:, 0]
    ratio = jnp.exp(logp - batch["logp_old"])
    adv = batch["advantages"]
    pg_loss = -jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
    ).mean()
    vf_loss = 0.5 * ((out["value"] - batch["returns"]) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return loss, {
        "policy_loss": pg_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "clip_frac": (jnp.abs(ratio - 1) > clip_eps).mean(),
    }


def compute_gae(
    rewards: np.ndarray,  # [T, N]
    values: np.ndarray,  # [T, N]
    dones: np.ndarray,  # [T, N]
    last_value: np.ndarray,  # [N]
    gamma: float,
    lam: float,
) -> tuple[np.ndarray, np.ndarray]:
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    gae = np.zeros_like(last_value)
    next_value = last_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    return adv, adv + values


@dataclass(frozen=True)
class PPOConfig(AlgorithmConfig):
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    ent_coeff: float = 0.01
    gae_lambda: float = 0.95
    num_epochs: int = 4
    minibatch_size: int = 128

    def build(self) -> "PPO":
        return PPO(self)


class PPO(Algorithm):
    def _make_learner(self) -> Learner:
        cfg = self.config

        def loss(params, module, batch):
            return ppo_loss(
                params, module, batch, cfg.clip_eps, cfg.vf_coeff, cfg.ent_coeff
            )

        return Learner(
            self.module, loss, make_adam(cfg.lr), mesh=cfg.mesh, seed=cfg.seed
        )

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample()
        self._record_episodes(samples)

        obs, acts, logp, advs, rets = [], [], [], [], []
        for s in samples:
            adv, ret = compute_gae(
                s["rewards"], s["values"], s["dones"], s["last_value"],
                cfg.gamma, cfg.gae_lambda,
            )
            obs.append(s["obs"].reshape(-1, s["obs"].shape[-1]))
            acts.append(s["actions"].reshape(-1))
            logp.append(s["logp"].reshape(-1))
            advs.append(adv.reshape(-1))
            rets.append(ret.reshape(-1))
        obs = np.concatenate(obs)
        acts = np.concatenate(acts)
        logp = np.concatenate(logp)
        advs = np.concatenate(advs)
        rets = np.concatenate(rets)
        advs = (advs - advs.mean()) / (advs.std() + 1e-8)

        n = len(obs)
        rng = np.random.default_rng(cfg.seed + self.iteration)
        metrics: dict = {}
        mb = min(cfg.minibatch_size, n)
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n - mb + 1, mb):
                idx = perm[start : start + mb]
                metrics = self.learner.update(
                    {
                        "obs": obs[idx],
                        "actions": acts[idx],
                        "logp_old": logp[idx],
                        "advantages": advs[idx],
                        "returns": rets[idx],
                    }
                )
        self.runners.set_weights(self.learner.get_weights())
        metrics["num_env_steps_sampled"] = n
        return metrics
