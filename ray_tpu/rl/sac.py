"""SAC (discrete): twin soft Q-networks, a stochastic policy, and an
auto-tuned entropy temperature (reference:
rllib/algorithms/sac/sac.py + sac_torch_learner; discrete variant per
Christodoulou 2019).

One jit update program covers all three objectives (Q, policy, alpha) on
a single packed param tree; target networks update by polyak averaging
on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, make_adam
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.module import MLPModule, RLModule, _dense, _dense_init
from ray_tpu.rl.replay import ReplayBuffer


@dataclass(frozen=True)
class SACModule(RLModule):
    """Policy trunk + twin Q MLPs packed in one param tree."""

    hidden: tuple = (64, 64)

    def _mlp_init(self, key, n_out):
        keys = jax.random.split(key, len(self.hidden) + 1)
        layers = []
        n_in = self.observation_size
        for i, h in enumerate(self.hidden):
            layers.append(_dense_init(keys[i], n_in, h))
            n_in = h
        layers.append(_dense_init(keys[-1], n_in, n_out, scale=0.01))
        return layers

    def _mlp(self, layers, x):
        for layer in layers[:-1]:
            x = jnp.tanh(_dense(layer, x))
        return _dense(layers[-1], x)

    def init(self, key: jax.Array):
        kp, k1, k2 = jax.random.split(key, 3)
        return {
            "policy": self._mlp_init(kp, self.num_actions),
            "q1": self._mlp_init(k1, self.num_actions),
            "q2": self._mlp_init(k2, self.num_actions),
            "log_alpha": jnp.zeros((), jnp.float32),
        }

    def q_values(self, params, obs):
        x = obs.astype(jnp.float32)
        return self._mlp(params["q1"], x), self._mlp(params["q2"], x)

    def forward(self, params, obs) -> dict:
        """Runner-compatible view: policy logits + soft state value."""
        x = obs.astype(jnp.float32)
        logits = self._mlp(params["policy"], x)
        q1, q2 = self.q_values(params, obs)
        probs = jax.nn.softmax(logits)
        logp = jax.nn.log_softmax(logits)
        alpha = jnp.exp(params["log_alpha"])
        value = (probs * (jnp.minimum(q1, q2) - alpha * logp)).sum(-1)
        return {"logits": logits, "value": value}


def sac_loss(params, module, batch, target_params, gamma, target_entropy):
    obs, next_obs = batch["obs"], batch["next_obs"]
    actions = batch["actions"]
    alpha = jnp.exp(params["log_alpha"])
    alpha_sg = jax.lax.stop_gradient(alpha)

    # Soft Bellman target from the TARGET twin Qs + current policy.
    logits_next = module._mlp(params["policy"], next_obs)
    probs_next = jax.nn.softmax(logits_next)
    logp_next = jax.nn.log_softmax(logits_next)
    q1t, q2t = module.q_values(target_params, next_obs)
    v_next = (
        probs_next * (jnp.minimum(q1t, q2t) - alpha_sg * logp_next)
    ).sum(-1)
    target_q = jax.lax.stop_gradient(
        batch["rewards"] + gamma * (1.0 - batch["dones"]) * v_next
    )

    q1, q2 = module.q_values(params, obs)
    q1_a = jnp.take_along_axis(q1, actions[:, None], axis=-1)[:, 0]
    q2_a = jnp.take_along_axis(q2, actions[:, None], axis=-1)[:, 0]
    q_loss = 0.5 * (
        ((q1_a - target_q) ** 2).mean() + ((q2_a - target_q) ** 2).mean()
    )

    # Policy: minimize E_pi[alpha*logpi - minQ] (exact expectation over
    # the discrete action set — no reparameterization needed).
    logits = module._mlp(params["policy"], obs)
    probs = jax.nn.softmax(logits)
    logp = jax.nn.log_softmax(logits)
    min_q = jax.lax.stop_gradient(jnp.minimum(q1, q2))
    pi_loss = (probs * (alpha_sg * logp - min_q)).sum(-1).mean()

    # Temperature: entropy tracks target_entropy.
    entropy = -(probs * logp).sum(-1)
    alpha_loss = (
        params["log_alpha"]
        * jax.lax.stop_gradient(entropy.mean() - target_entropy)
    )

    loss = q_loss + pi_loss + alpha_loss
    return loss, {
        "q_loss": q_loss,
        "policy_loss": pi_loss,
        "alpha": alpha,
        "entropy": entropy.mean(),
    }


@dataclass(frozen=True)
class SACConfig(AlgorithmConfig):
    buffer_capacity: int = 50_000
    batch_size: int = 256
    learning_starts: int = 1_000
    tau: float = 0.01  # polyak target update rate
    updates_per_step: int = 8
    target_entropy: float | None = None  # default 0.5*log(A)

    def build(self) -> "SAC":
        return SAC(self)


class SAC(Algorithm):
    def __init__(self, config: SACConfig):
        super().__init__(config)
        probe_obs = self.module.observation_size
        self.buffer = ReplayBuffer(
            config.buffer_capacity, probe_obs, seed=config.seed
        )
        self.target_params = jax.tree.map(
            lambda a: a, self.learner.params
        )

        tau = config.tau

        @jax.jit
        def polyak(target, online):
            return jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, target, online
            )

        self._polyak = polyak

    def _make_module(self, probe_env):
        return SACModule(
            observation_size=probe_env.observation_size,
            num_actions=probe_env.num_actions,
            hidden=self.config.hidden,
        )

    def _make_learner(self) -> Learner:
        cfg = self.config
        target_entropy = cfg.target_entropy
        if target_entropy is None:
            target_entropy = 0.5 * float(np.log(self.module.num_actions))

        def loss(params, module, batch, target_params):
            return sac_loss(
                params, module, batch, target_params, cfg.gamma,
                target_entropy,
            )

        return Learner(
            self.module, loss, make_adam(cfg.lr), mesh=cfg.mesh,
            seed=cfg.seed,
        )

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample()
        self._record_episodes(samples)
        for s in samples:
            T, N = s["rewards"].shape
            obs = s["obs"].reshape(T * N, -1)
            next_obs = np.concatenate(
                [s["obs"][1:], s["next_obs"][None]], axis=0
            ).reshape(T * N, -1)
            self.buffer.add_batch(
                obs,
                s["actions"].reshape(-1),
                s["rewards"].reshape(-1),
                s["dones"].reshape(-1),
                next_obs,
            )
        metrics: dict = {}
        if len(self.buffer) >= max(cfg.learning_starts, cfg.batch_size):
            for _ in range(cfg.updates_per_step):
                batch = self.buffer.sample(cfg.batch_size)
                metrics = self.learner.update(batch, self.target_params)
                self.target_params = self._polyak(
                    self.target_params, self.learner.params
                )
        self.runners.set_weights(self.learner.get_weights())
        metrics["num_env_steps_sampled"] = sum(
            s["rewards"].size for s in samples
        )
        return metrics
