"""CPU collective backend: host tensors over the runtime RPC.

Fills the role of the reference's gloo backend (reference:
python/ray/util/collective/collective_group/torch_gloo_collective_group.py)
as the CPU baseline and test stand-in. Topology is hub-reduce: rank 0
collects contributions, reduces with numpy, and answers every member's
in-flight RPC with the result — one round trip per op, fine for control-
plane-sized tensors (accelerator tensors take the XLA backends).

Rendezvous replaces the reference's NCCLUniqueID named-actor store
(nccl_collective_group.py:29): members publish rank→addr in the head KV
and poll until the group is complete.

Fault tolerance (reference: NCCL abort + destroy_collective_group
semantics; "Efficient AllReduce with Stragglers" motivates the
telemetry):

- Every op and the rendezvous itself run under a deadline. The hub arms
  a timer when an op's first contribution arrives; expiry answers every
  waiting member with a structured timeout naming the missing ranks
  (raised member-side as CollectiveTimeoutError) and fire-and-forgets a
  head probe so a genuinely dead member is *confirmed* dead instead of
  timing out again next op.
- Members register with the head (addr + node addr + worker id). When
  the head declares a member dead — node heartbeat loss, worker reap,
  or a probe — it publishes on the "collective" channel; survivors
  poison the group and fail in-flight and future ops with
  CollectiveMemberDiedError immediately instead of burning the full
  timeout. The hub additionally watches member connections: a dropped
  conn aborts pending ops at once.
- A poisoned (or op-desynced) group is repaired by reform(): survivors
  re-rendezvous under a bumped epoch (fresh KV keys, fresh op
  sequence), re-ranked densely, with the lowest surviving rank as the
  new hub.
- Straggler telemetry: the hub records per-op first→last contribution
  lag and the slowest rank (util/metrics.py histogram + counter), so a
  chronically slow member is visible before it becomes a timeout.
- Partial K-of-N mode ("Efficient AllReduce with Stragglers",
  arXiv:2505.23523): ``allreduce(..., min_ranks=K, grace_s=...)`` arms a
  SECOND, earlier timer when the first contribution arrives. If the
  grace sub-deadline passes with ≥K contributions in hand (or the K-th
  lands after it), the hub completes the op over the contributors —
  SUM rescaled by world/K so downstream mean math stays correct — and
  answers everyone with typed PartialResult metadata naming the skipped
  ranks. A "partial" tombstone keeps the op's reply around so a
  straggler's late contribution is acked-and-discarded with the same
  result (it rejoins op-sequence-synchronized instead of hanging or
  desyncing). The hard deadline still raises CollectiveTimeoutError
  when even K never arrive. Skips feed the straggler stats, the
  ray_tpu_collective_partial_* metrics, and — past a sliding-window
  threshold — an escalation report to the head that triggers the
  chronic-straggler drain-and-replace path.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

import numpy as np

from ray_tpu._private import rpc
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu.collective.flight_recorder import record_op, record_partial
from ray_tpu.collective.types import (
    CollectiveGroupDestroyedError,
    CollectiveMemberDiedError,
    CollectiveTimeoutError,
    PartialResult,
    ReduceOp,
)
from ray_tpu.util.metrics import Counter, Histogram

_REDUCERS = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}

# Extra member-side wait beyond the hub's deadline: the hub answers
# expiry itself, so a member only hits its own backstop when the hub
# process is gone or wedged.
_HUB_GRACE_S = 5.0

_LAG_HIST = Histogram(
    "collective_straggler_lag_s",
    "first→last contribution spread per collective op (hub-measured)",
    boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
    tag_keys=("group", "op"),
)
_STRAGGLER_TOTAL = Counter(
    "collective_straggler_total",
    "ops in which this rank was the slowest (or missing) contributor",
    tag_keys=("group", "rank"),
)
_ABORT_TOTAL = Counter(
    "collective_abort_total",
    "collective ops aborted by timeout or member death",
    tag_keys=("group", "reason"),
)


class _Pending:
    __slots__ = ("contrib", "futures", "arrived", "started", "arrive_ts",
                 "timer", "grace_timer", "grace_passed", "min_ranks",
                 "grace_s", "meta")

    def __init__(self, world: int):
        self.contrib: list = [None] * world
        self.futures: list = []
        self.arrived = 0
        self.started = time.monotonic()
        self.arrive_ts: dict[int, float] = {}
        self.timer: asyncio.TimerHandle | None = None
        # Partial K-of-N state (None min_ranks = classic all-N op; the
        # partial path is never entered, byte-identical behavior).
        self.grace_timer: asyncio.TimerHandle | None = None
        self.grace_passed = False
        self.min_ranks: int | None = None
        self.grace_s: float = 0.0
        self.meta: dict = {}

    def cancel_timers(self):
        if self.timer is not None:
            self.timer.cancel()
        if self.grace_timer is not None:
            self.grace_timer.cancel()


def _pack(value) -> tuple[bytes, list[bytes]]:
    s = serialize(value).materialize_buffers()
    return s.inband, s.buffers


def _unpack(packed: tuple) -> Any:
    return deserialize(packed[0], packed[1])


def _default_timeout() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_TIMEOUT_S")


def _default_partial_grace() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_PARTIAL_GRACE_S")


class CpuGroup:
    def __init__(
        self,
        core,
        group_name: str,
        world_size: int,
        rank: int,
        timeout_s: float | None = None,
        epoch: int = 0,
    ):
        self.core = core  # CoreWorker (for RPC + head KV)
        self.base_name = group_name
        self.epoch = epoch
        # Epoch-scoped internal name: a reformed group must never
        # rendezvous against (or serve ops for) a previous incarnation's
        # KV keys / handlers.
        self.name = group_name if epoch == 0 else f"{group_name}~e{epoch}"
        self.world = world_size
        self.rank = rank
        self.timeout_s = (
            _default_timeout() if timeout_s is None else float(timeout_s)
        )
        self.root_addr: str | None = None
        self._seq = 0
        self._pending: dict[tuple, _Pending] = {}  # (op_kind, seq) → state
        # (src, seq) → (deque[payload], deque[waiter futures])
        self._mailbox: dict[tuple, tuple] = {}
        self._dead: set[int] = set()      # ranks declared dead (poison)
        self._destroyed = False
        self._inflight: set[asyncio.Future] = set()  # member-side calls
        self._rank_conns: dict[int, rpc.Connection] = {}  # hub-side
        # straggler telemetry (hub-side): rank → times slowest
        self._straggler_counts: dict[int, int] = {}
        self._ops_completed = 0
        self._last_lag_s = 0.0
        # Partial-mode state (hub-side). _partial_done is the tombstone
        # cache: (kind, seq) → the completed op's reply, kept so a
        # straggler's LATE contribution is acked with the same partial
        # result instead of opening a fresh pending op that can only
        # time out. _skip_events is the sliding window feeding the
        # chronic-skip escalation to the head.
        self._partial_done: "dict[tuple, dict]" = {}
        self._partial_ops = 0
        self._skip_counts: dict[int, int] = {}
        self._skip_events: list[tuple[float, int]] = []
        self._skip_reported: set[int] = set()
        if rank == 0:
            self.core.ext_handlers[f"col_op:{self.name}"] = self._on_op
        self.core.ext_handlers[f"col_sendrecv:{self.name}"] = self._on_sendrecv

    # --------------------------------------------------------- bootstrap
    async def init(self, timeout_s: float | None = None):
        """Rendezvous through the head KV, bounded by the group deadline:
        members that never join surface as CollectiveTimeoutError with
        the missing ranks, not an infinite poll loop."""
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + t
        key = f"collective:{self.name}:{self.rank}"
        await self.core.head.call("kv_put", key=key, value=self.core.addr.encode())
        # Membership registration: the head's table is what lets node /
        # worker death fan out to survivors as a typed abort.
        try:
            await self.core.head.call(
                "collective_register",
                group=self.base_name,
                rank=self.rank,
                epoch=self.epoch,
                addr=self.core.addr,
                node_addr=getattr(self.core, "node_addr", None),
                worker_id=getattr(self.core, "worker_id", None),
            )
        except rpc.RpcError:
            pass  # older head without the membership table: deadline
            # enforcement still works, only death fan-out is lost
        import ray_tpu.collective as _col

        await _col._ensure_death_watch(self.core)
        prefix = f"collective:{self.name}:"
        while True:
            reply = await self.core.head.call("kv_keys", prefix=prefix)
            present = set()
            for k in reply.get("keys", []):
                tail = k[len(prefix):]
                if tail.isdigit():
                    present.add(int(tail))
            if len(present & set(range(self.world))) == self.world:
                break
            if time.monotonic() > deadline:
                missing = sorted(set(range(self.world)) - present)
                await self._cleanup_rendezvous(key)
                raise CollectiveTimeoutError(
                    self.base_name, "rendezvous", t, missing_ranks=missing
                )
            await asyncio.sleep(0.05)
        reply = await self.core.head.call(
            "kv_get", key=f"{prefix}0"
        )
        self.root_addr = reply["value"].decode()

    async def _cleanup_rendezvous(self, key: str):
        """Failed init must not leave a half-registered member behind."""
        self.core.ext_handlers.pop(f"col_op:{self.name}", None)
        self.core.ext_handlers.pop(f"col_sendrecv:{self.name}", None)
        try:
            await self.core.head.call("kv_del", key=key)
            await self.core.head.call(
                "collective_deregister",
                group=self.base_name,
                epoch=self.epoch,
                rank=self.rank,
            )
        except rpc.RpcError:
            pass

    async def destroy(self, reason: str = "destroyed"):
        """Tear down AND fail everything in flight: hub-side pending op
        futures, member-side in-flight calls, and mailbox recv waiters —
        an awaiting coroutine must never stay pending past destroy.

        A tombstone handler replaces the hub's op endpoint so a straggler
        member's LATE op against this incarnation gets a typed answer
        (``reason`` of "reformed" lets auto_reform rejoin the new epoch)
        instead of an unknown-method RpcError."""
        self._destroyed = True

        async def _tombstone(conn, **kw):
            return {"ok": False, "error": reason}

        if self.rank == 0:
            self.core.ext_handlers[f"col_op:{self.name}"] = _tombstone
        self.core.ext_handlers.pop(f"col_sendrecv:{self.name}", None)
        for key, st in list(self._pending.items()):
            st.cancel_timers()
            for _rank, fut in st.futures:
                if not fut.done():
                    fut.set_result({"ok": False, "error": "destroyed"})
        self._pending.clear()
        self._partial_done.clear()
        for call in list(self._inflight):
            call.cancel()
        for payloads, waiters in self._mailbox.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_exception(
                        CollectiveGroupDestroyedError(self.base_name, "recv")
                    )
        self._mailbox.clear()
        try:
            await self.core.head.call(
                "collective_deregister",
                group=self.base_name,
                epoch=self.epoch,
                rank=self.rank,
            )
        except rpc.RpcError:
            pass
        if self.rank == 0:
            for r in range(self.world):
                try:
                    await self.core.head.call(
                        "kv_del", key=f"collective:{self.name}:{r}"
                    )
                except rpc.RpcError:
                    pass

    # ------------------------------------------------- abort-and-reform
    async def reform(self, timeout_s: float | None = None) -> "CpuGroup":
        """Re-run rendezvous with the surviving ranks under a bumped
        epoch: new dense ranks (order-preserving), new world size, the
        lowest surviving rank becomes the hub. Also repairs a desynced
        group after an op timeout (dead set empty → same shape, fresh
        op sequence)."""
        survivors = [r for r in range(self.world) if r not in self._dead]
        if self.rank not in survivors:
            raise CollectiveMemberDiedError(
                self.base_name,
                "reform",
                dead_ranks=sorted(self._dead),
                detail="this rank is itself marked dead",
            )
        g = CpuGroup(
            self.core,
            self.base_name,
            len(survivors),
            survivors.index(self.rank),
            timeout_s=self.timeout_s if timeout_s is None else timeout_s,
            epoch=self.epoch + 1,
        )
        await self.destroy(reason="reformed")
        await g.init()
        g.auto_reform = getattr(self, "auto_reform", False)
        return g

    # ------------------------------------------------ death propagation
    def _on_member_dead(self, ranks, epoch: int | None = None):
        """Head fan-out (or hub conn-loss) declared members dead: poison
        the group and abort everything in flight, now."""
        if self._destroyed:
            return
        if epoch is not None and epoch != self.epoch:
            return  # stale event about a previous incarnation
        dead = {int(r) for r in ranks} - {self.rank}
        if not dead or dead <= self._dead:
            return
        self._dead |= dead
        _ABORT_TOTAL.inc(
            tags={"group": self.base_name, "reason": "member_died"}
        )
        reply = {
            "ok": False,
            "error": "member_died",
            "dead_ranks": sorted(self._dead),
        }
        for key, st in list(self._pending.items()):
            st.cancel_timers()
            for _rank, fut in st.futures:
                if not fut.done():
                    fut.set_result(dict(reply))
        self._pending.clear()
        for call in list(self._inflight):
            call.cancel()
        err = CollectiveMemberDiedError(
            self.base_name, "recv", dead_ranks=sorted(self._dead)
        )
        for payloads, waiters in self._mailbox.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_exception(err)

    def _watch_conn(self, rank: int, conn: rpc.Connection):
        """Hub-side: a member's dropped connection is a death signal —
        abort its group-mates' pending ops instead of waiting out the
        deadline (reference: NCCL comm abort on peer loss)."""
        if self._rank_conns.get(rank) is conn:
            return
        self._rank_conns[rank] = conn
        prev = conn.on_close

        def on_close(c, _prev=prev, _rank=rank):
            if _prev:
                _prev(c)
            if (
                not self._destroyed
                and self._rank_conns.get(_rank) is c
            ):
                self._on_member_dead([_rank])

        conn.on_close = on_close

    def _check_alive(self, op: str):
        if self._destroyed:
            raise CollectiveGroupDestroyedError(self.base_name, op)
        if self._dead:
            raise CollectiveMemberDiedError(
                self.base_name,
                op,
                dead_ranks=sorted(self._dead),
                detail="group is poisoned; reform_group() to continue",
            )

    def _probe_missing(self, ranks):
        """Fire-and-forget head probe: confirm whether silent ranks are
        dead so the next failure is a fast typed abort, and a dead node
        is reaped without waiting out HEALTH_TIMEOUT_S."""
        async def probe():
            try:
                await self.core.head.call(
                    "collective_probe",
                    group=self.base_name,
                    ranks=list(ranks),
                )
            except rpc.RpcError:
                pass

        asyncio.ensure_future(probe())

    # -------------------------------------------------------- hub (rank0)
    async def _on_op(
        self, conn, kind: str, seq: int, rank: int, payload: tuple, meta: dict
    ):
        if self._destroyed:
            return {"ok": False, "error": "destroyed"}
        if self._dead:
            return {
                "ok": False,
                "error": "member_died",
                "dead_ranks": sorted(self._dead),
            }
        key = (kind, seq)
        done = self._partial_done.get(key)
        if done is not None:
            # This op already partially completed without this rank:
            # ack-and-discard the late contribution, answering with the
            # SAME rescaled result + partial metadata (the straggler
            # rejoins typed and op-sequence-synchronized; a fresh
            # pending entry here could only hang until the deadline).
            return done
        st = self._pending.get(key)
        if st is None:
            st = self._pending[key] = _Pending(self.world)
            timeout = float(meta.get("timeout_s") or self.timeout_s)
            loop = asyncio.get_running_loop()
            st.timer = loop.call_later(timeout, self._expire, key, timeout)
            min_ranks = meta.get("min_ranks")
            if min_ranks is not None and kind == "allreduce":
                # Two-stage timer: the grace sub-deadline is measured
                # from the FASTEST arrival — which is this one, the
                # contribution that created the pending entry.
                st.min_ranks = max(1, min(int(min_ranks), self.world))
                st.grace_s = float(
                    meta.get("grace_s") or _default_partial_grace()
                )
                st.meta = dict(meta)
                st.grace_timer = loop.call_later(
                    st.grace_s, self._grace_fire, key
                )
        self._watch_conn(rank, conn)
        st.contrib[rank] = _unpack(payload)
        st.arrived += 1
        st.arrive_ts[rank] = time.monotonic()
        fut = asyncio.get_running_loop().create_future()
        st.futures.append((rank, fut))
        if st.arrived == self.world:
            st.cancel_timers()
            self._record_op_stats(kind, st)
            self._complete(key, st, kind, meta)
        elif (
            st.grace_passed
            and st.min_ranks is not None
            and st.arrived >= st.min_ranks
        ):
            # The K-th contribution landed after the grace sub-deadline:
            # proceed now rather than waiting out the hard deadline.
            self._complete_partial(key, st, kind, meta)
        return await fut

    def _grace_fire(self, key: tuple):
        """Grace sub-deadline: proceed with the K-of-N contributions in
        hand; with fewer than K, keep waiting (the K-th arrival or the
        hard deadline resolves the op)."""
        st = self._pending.get(key)
        if st is None:
            return
        st.grace_passed = True
        if st.min_ranks is not None and st.arrived >= st.min_ranks:
            self._complete_partial(key, st, key[0], st.meta)

    def _expire(self, key: tuple, timeout: float):
        """Hub deadline: answer every waiting member with the missing
        ranks, then probe them — a dead member becomes a confirmed
        death, a merely slow one shows up in the straggler stats."""
        st = self._pending.pop(key, None)
        if st is None:
            return
        st.cancel_timers()
        missing = [r for r in range(self.world) if st.contrib[r] is None]
        _ABORT_TOTAL.inc(tags={"group": self.base_name, "reason": "timeout"})
        for r in missing:
            self._straggler_counts[r] = self._straggler_counts.get(r, 0) + 1
            _STRAGGLER_TOTAL.inc(
                tags={"group": self.base_name, "rank": str(r)}
            )
        reply = {
            "ok": False,
            "error": "timeout",
            "missing_ranks": missing,
            "timeout_s": timeout,
            "op": key[0],
        }
        for _rank, fut in st.futures:
            if not fut.done():
                fut.set_result(dict(reply))
        self._probe_missing(missing)

    def _record_op_stats(self, kind: str, st: _Pending):
        self._ops_completed += 1
        if len(st.arrive_ts) < 2:
            return
        first = min(st.arrive_ts.values())
        last = max(st.arrive_ts.values())
        self._last_lag_s = last - first
        slowest = max(st.arrive_ts, key=st.arrive_ts.get)
        self._straggler_counts[slowest] = (
            self._straggler_counts.get(slowest, 0) + 1
        )
        _LAG_HIST.observe(
            self._last_lag_s, tags={"group": self.base_name, "op": kind}
        )
        _STRAGGLER_TOTAL.inc(
            tags={"group": self.base_name, "rank": str(slowest)}
        )

    def straggler_stats(self) -> dict:
        """Hub-side per-rank slowest/missing counts (empty off-hub).
        ``partial_ops`` / ``skip_counts`` cover the K-of-N mode: how
        many ops completed without someone, and who got skipped."""
        return {
            "ops_completed": self._ops_completed,
            "last_lag_s": self._last_lag_s,
            "slowest_counts": dict(self._straggler_counts),
            "partial_ops": self._partial_ops,
            "skip_counts": dict(self._skip_counts),
        }

    # -------------------------------------------- partial K-of-N (hub)
    def _complete_partial(self, key, st: _Pending, kind: str, meta: dict):
        """Complete an op over the K..N-1 contributions in hand: reduce
        the contributors, rescale SUM by world/K (so result/world is the
        mean over actual contributors), answer every waiter with the
        result + partial metadata, and leave a tombstone reply for the
        stragglers' late contributions."""
        del self._pending[key]
        st.cancel_timers()
        contributed = sorted(st.arrive_ts)
        skipped = [r for r in range(self.world) if st.contrib[r] is None]
        op = ReduceOp(meta.get("op", "sum"))
        stacked = np.stack([st.contrib[r] for r in contributed])
        result = _REDUCERS[op](stacked)
        if op is ReduceOp.SUM:
            result = result * (self.world / float(len(contributed)))
        self._partial_ops += 1
        self._ops_completed += 1
        record_partial(self.base_name, kind, skipped)
        now = time.monotonic()
        for r in skipped:
            self._skip_counts[r] = self._skip_counts.get(r, 0) + 1
            self._straggler_counts[r] = self._straggler_counts.get(r, 0) + 1
            _STRAGGLER_TOTAL.inc(
                tags={"group": self.base_name, "rank": str(r)}
            )
            self._skip_events.append((now, r))
        partial_meta = {
            "contributed": contributed,
            "skipped": skipped,
            "world": self.world,
        }
        reply = {
            "ok": True,
            "payload": _pack(result),
            "partial": partial_meta,
        }
        for rank, fut in st.futures:
            if not fut.done():
                fut.set_result(dict(reply))
        # Tombstone for the stragglers (bounded: ops complete in seq
        # order, old tombstones can no longer be asked for).
        self._partial_done[key] = reply
        while len(self._partial_done) > 128:
            self._partial_done.pop(next(iter(self._partial_done)))
        self._escalate_chronic_skips(now)

    def _escalate_chronic_skips(self, now: float):
        """Report a rank whose skip count crossed the sliding-window
        threshold to the head — feeding the existing chronic-straggler
        drain-and-replace escalation (autoscaler straggler_drain) from
        inside the op instead of waiting on metric-snapshot latency."""
        from ray_tpu._private import config

        window = config.get("COLLECTIVE_SKIP_WINDOW_S")
        threshold = config.get("COLLECTIVE_SKIP_DRAIN_THRESHOLD")
        cutoff = now - window
        self._skip_events = [e for e in self._skip_events if e[0] >= cutoff]
        counts: dict[int, int] = {}
        for _ts, r in self._skip_events:
            counts[r] = counts.get(r, 0) + 1
        for r, n in counts.items():
            if n < threshold or r in self._skip_reported:
                continue
            self._skip_reported.add(r)

            async def report(rank=r, skips=n):
                try:
                    await self.core.head.call(
                        "collective_straggler_report",
                        group=self.base_name,
                        rank=rank,
                        skips=skips,
                        window_s=window,
                    )
                except rpc.RpcError:
                    pass  # older head: the metric-snapshot path still
                    # carries the signal, only the fast escalation is lost

            asyncio.ensure_future(report())

    def _complete(self, key, st: _Pending, kind: str, meta: dict):
        del self._pending[key]
        op = ReduceOp(meta.get("op", "sum"))
        if kind == "allreduce" or kind == "reduce":
            result = _REDUCERS[op](np.stack(st.contrib))
        elif kind == "allgather":
            result = list(st.contrib)
        elif kind == "reducescatter":
            red = _REDUCERS[op](np.stack(st.contrib))
            result = np.array_split(red, self.world, axis=0)
        elif kind == "broadcast":
            result = st.contrib[meta.get("root", 0)]
        elif kind == "barrier":
            result = None
        else:
            raise rpc.RpcError(f"unknown collective {kind}")
        for rank, fut in st.futures:
            if fut.done():
                continue
            if kind == "reducescatter":
                fut.set_result({"ok": True, "payload": _pack(result[rank])})
            elif kind == "reduce" and rank != meta.get("root", 0):
                fut.set_result({"ok": True, "payload": _pack(None)})
            else:
                fut.set_result({"ok": True, "payload": _pack(result)})

    # ----------------------------------------------------------- verbs
    def _interpret(self, kind: str, reply: dict):
        if reply.get("ok"):
            value = _unpack(reply["payload"]) if "payload" in reply else None
            partial = reply.get("partial")
            if partial is not None:
                return PartialResult(
                    value=value,
                    contributed=[int(r) for r in partial["contributed"]],
                    skipped=[int(r) for r in partial["skipped"]],
                    world=int(partial["world"]),
                )
            return value
        error = reply.get("error")
        if error == "timeout":
            raise CollectiveTimeoutError(
                self.base_name,
                kind,
                reply.get("timeout_s"),
                missing_ranks=reply.get("missing_ranks"),
            )
        if error in ("destroyed", "reformed"):
            raise CollectiveGroupDestroyedError(
                self.base_name,
                kind,
                detail="reformed" if error == "reformed" else "",
            )
        dead = [int(r) for r in reply.get("dead_ranks") or []]
        self._dead.update(d for d in dead if d != self.rank)
        raise CollectiveMemberDiedError(
            self.base_name, kind, dead_ranks=dead
        )

    async def _op(
        self, kind: str, tensor: Any, timeout_s: float | None = None, **meta
    ):
        self._check_alive(kind)
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        self._seq += 1
        seq = self._seq
        # Deterministic straggler injection (RAY_TPU_STRAGGLER_DELAY=
        # "rank:seconds,…"): the named ranks are late to every
        # contribution — the chaos knob the partial-collective and
        # straggler-stats tests are built on. Read per call so tests
        # can flip it at runtime; zero-cost when the spec is unset.
        from ray_tpu._private.test_utils import straggler_delay_for_rank

        delay = straggler_delay_for_rank(self.rank)
        if delay > 0:
            await asyncio.sleep(delay)
        wall_start = time.time()
        t0 = time.perf_counter()
        try:
            conn = await self.core._connect(self.root_addr)
        except rpc.ConnectionLost:
            self._dead.add(0)
            raise CollectiveMemberDiedError(
                self.base_name, kind, dead_ranks=[0],
                detail="cannot reach the hub rank",
            )
        call = asyncio.ensure_future(
            conn.call(
                f"col_op:{self.name}",
                kind=kind,
                seq=seq,
                rank=self.rank,
                payload=_pack(tensor),
                meta={**meta, "timeout_s": t},
            )
        )
        self._inflight.add(call)
        try:
            # The hub answers its own deadline; the grace-padded backstop
            # only fires when the hub process itself is gone or wedged.
            reply = await asyncio.wait_for(call, t + _HUB_GRACE_S)
        except asyncio.TimeoutError:
            self._probe_missing([0])
            raise CollectiveTimeoutError(
                self.base_name, kind, t,
                detail="hub rank 0 did not answer within the deadline",
            )
        except asyncio.CancelledError:
            # destroy() / death fan-out cancelled the in-flight call.
            if self._destroyed:
                raise CollectiveGroupDestroyedError(self.base_name, kind)
            if self._dead:
                raise CollectiveMemberDiedError(
                    self.base_name, kind, dead_ranks=sorted(self._dead)
                )
            raise
        except rpc.ConnectionLost:
            self._dead.add(0)
            raise CollectiveMemberDiedError(
                self.base_name, kind, dead_ranks=[0],
                detail="hub connection lost",
            )
        finally:
            self._inflight.discard(call)
        result = self._interpret(kind, reply)
        record_op(
            self.base_name, kind, "cpu", self.world, tensor,
            wall_start, time.perf_counter() - t0,
        )
        return result

    async def allreduce(
        self,
        tensor,
        op=ReduceOp.SUM,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
    ):
        """``min_ranks=K`` enables partial K-of-N mode: the hub proceeds
        once K contributions are in hand after ``grace_s`` past the
        fastest arrival, returning PartialResult metadata; with the
        default None the classic all-N path runs unchanged."""
        meta: dict = {"op": op.value}
        if min_ranks is not None:
            if not 1 <= int(min_ranks) <= self.world:
                raise ValueError(
                    f"min_ranks {min_ranks} out of range 1..{self.world}"
                )
            meta["min_ranks"] = int(min_ranks)
            if grace_s is not None:
                meta["grace_s"] = float(grace_s)
        out = await self._op(
            "allreduce", np.asarray(tensor), timeout_s=timeout_s, **meta
        )
        if min_ranks is not None and not isinstance(out, PartialResult):
            # Everyone made the grace window: same typed envelope, no
            # skips — callers in partial mode always see PartialResult.
            out = PartialResult(
                value=out,
                contributed=list(range(self.world)),
                skipped=[],
                world=self.world,
            )
        return out

    async def reduce(self, tensor, root=0, op=ReduceOp.SUM, timeout_s=None):
        return await self._op(
            "reduce", np.asarray(tensor), timeout_s=timeout_s,
            root=root, op=op.value,
        )

    async def broadcast(self, tensor, root=0, timeout_s=None):
        return await self._op(
            "broadcast", np.asarray(tensor), timeout_s=timeout_s, root=root
        )

    async def allgather(self, tensor, timeout_s=None):
        return await self._op(
            "allgather", np.asarray(tensor), timeout_s=timeout_s
        )

    async def reducescatter(self, tensor, op=ReduceOp.SUM, timeout_s=None):
        return await self._op(
            "reducescatter", np.asarray(tensor), timeout_s=timeout_s,
            op=op.value,
        )

    async def barrier(self, timeout_s=None):
        await self._op("barrier", None, timeout_s=timeout_s)

    # ------------------------------------------------------- send / recv
    # Mailbox is a queue per (src, seq) so back-to-back sends with the
    # same tag enqueue rather than clobbering an already-resolved future.
    def _mail_queues(self, key):
        q = self._mailbox.get(key)
        if q is None:
            from collections import deque

            q = self._mailbox[key] = (deque(), deque())  # payloads, waiters
        return q

    async def _on_sendrecv(self, conn, src_rank: int, seq: int, payload: tuple):
        payloads, waiters = self._mail_queues((src_rank, seq))
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return {"ok": True}
        payloads.append(payload)
        return {"ok": True}

    async def send(self, tensor, dst_rank: int, seq: int = 0, timeout_s=None):
        self._check_alive("send")
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        arr = np.asarray(tensor)
        wall_start = time.time()
        t0 = time.perf_counter()

        async def _send():
            reply = await self.core.head.call(
                "kv_get", key=f"collective:{self.name}:{dst_rank}"
            )
            if not reply["ok"]:
                raise rpc.RpcError(
                    f"rank {dst_rank} not in group {self.name}"
                )
            conn = await self.core._connect(reply["value"].decode())
            await conn.call(
                f"col_sendrecv:{self.name}",
                src_rank=self.rank,
                seq=seq,
                payload=_pack(arr),
            )

        try:
            await asyncio.wait_for(_send(), t)
        except asyncio.TimeoutError:
            raise CollectiveTimeoutError(
                self.base_name, "send", t, missing_ranks=[dst_rank]
            )
        record_op(
            self.base_name, "send", "cpu", self.world, arr,
            wall_start, time.perf_counter() - t0,
        )

    async def recv(self, src_rank: int, seq: int = 0, timeout_s=None):
        self._check_alive("recv")
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        wall_start = time.time()
        t0 = time.perf_counter()
        payloads, waiters = self._mail_queues((src_rank, seq))
        if payloads:
            result = _unpack(payloads.popleft())
        else:
            fut = asyncio.get_running_loop().create_future()
            waiters.append(fut)
            try:
                result = _unpack(await asyncio.wait_for(fut, t))
            except asyncio.TimeoutError:
                raise CollectiveTimeoutError(
                    self.base_name, "recv", t, missing_ranks=[src_rank]
                )
        record_op(
            self.base_name, "recv", "cpu", self.world, result,
            wall_start, time.perf_counter() - t0,
        )
        return result
