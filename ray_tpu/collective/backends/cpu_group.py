"""CPU collective backend: host tensors over the runtime RPC.

Fills the role of the reference's gloo backend (reference:
python/ray/util/collective/collective_group/torch_gloo_collective_group.py)
as the CPU baseline and test stand-in. Topology is hub-reduce: rank 0
collects contributions, reduces with numpy, and answers every member's
in-flight RPC with the result — one round trip per op, fine for control-
plane-sized tensors (accelerator tensors take the XLA backends).

Rendezvous replaces the reference's NCCLUniqueID named-actor store
(nccl_collective_group.py:29): members publish rank→addr in the head KV
and poll until the group is complete.
"""

from __future__ import annotations

import asyncio
from typing import Any

import numpy as np

from ray_tpu._private import rpc
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu.collective.types import ReduceOp

_REDUCERS = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}


class _Pending:
    __slots__ = ("contrib", "futures", "arrived")

    def __init__(self, world: int):
        self.contrib: list = [None] * world
        self.futures: list = []
        self.arrived = 0


def _pack(value) -> tuple[bytes, list[bytes]]:
    s = serialize(value).materialize_buffers()
    return s.inband, s.buffers


def _unpack(packed: tuple) -> Any:
    return deserialize(packed[0], packed[1])


class CpuGroup:
    def __init__(self, core, group_name: str, world_size: int, rank: int):
        self.core = core  # CoreWorker (for RPC + head KV)
        self.name = group_name
        self.world = world_size
        self.rank = rank
        self.root_addr: str | None = None
        self._seq = 0
        self._pending: dict[tuple, _Pending] = {}  # (op_kind, seq) → state
        # (src, seq) → (deque[payload], deque[waiter futures])
        self._mailbox: dict[tuple, tuple] = {}
        if rank == 0:
            self.core.ext_handlers[f"col_op:{self.name}"] = self._on_op
        self.core.ext_handlers[f"col_sendrecv:{self.name}"] = self._on_sendrecv

    # --------------------------------------------------------- bootstrap
    async def init(self):
        key = f"collective:{self.name}:{self.rank}"
        await self.core.head.call("kv_put", key=key, value=self.core.addr.encode())
        root_key = f"collective:{self.name}:0"
        while True:
            reply = await self.core.head.call("kv_get", key=root_key)
            if reply["ok"]:
                self.root_addr = reply["value"].decode()
                break
            await asyncio.sleep(0.05)

    async def destroy(self):
        self.core.ext_handlers.pop(f"col_op:{self.name}", None)
        self.core.ext_handlers.pop(f"col_sendrecv:{self.name}", None)
        if self.rank == 0:
            for r in range(self.world):
                await self.core.head.call(
                    "kv_del", key=f"collective:{self.name}:{r}"
                )

    # -------------------------------------------------------- hub (rank0)
    async def _on_op(
        self, conn, kind: str, seq: int, rank: int, payload: tuple, meta: dict
    ):
        key = (kind, seq)
        st = self._pending.get(key)
        if st is None:
            st = self._pending[key] = _Pending(self.world)
        st.contrib[rank] = _unpack(payload)
        st.arrived += 1
        fut = asyncio.get_running_loop().create_future()
        st.futures.append((rank, fut))
        if st.arrived == self.world:
            self._complete(key, st, kind, meta)
        return await fut

    def _complete(self, key, st: _Pending, kind: str, meta: dict):
        del self._pending[key]
        op = ReduceOp(meta.get("op", "sum"))
        if kind == "allreduce" or kind == "reduce":
            result = _REDUCERS[op](np.stack(st.contrib))
        elif kind == "allgather":
            result = list(st.contrib)
        elif kind == "reducescatter":
            red = _REDUCERS[op](np.stack(st.contrib))
            result = np.array_split(red, self.world, axis=0)
        elif kind == "broadcast":
            result = st.contrib[meta.get("root", 0)]
        elif kind == "barrier":
            result = None
        else:
            raise rpc.RpcError(f"unknown collective {kind}")
        for rank, fut in st.futures:
            if fut.done():
                continue
            if kind == "reducescatter":
                fut.set_result(_pack(result[rank]))
            elif kind == "reduce" and rank != meta.get("root", 0):
                fut.set_result(_pack(None))
            else:
                fut.set_result(_pack(result))

    # ----------------------------------------------------------- verbs
    async def _op(self, kind: str, tensor: Any, **meta):
        self._seq += 1
        conn = await self.core._connect(self.root_addr)
        reply = await conn.call(
            f"col_op:{self.name}",
            kind=kind,
            seq=self._seq,
            rank=self.rank,
            payload=_pack(tensor),
            meta=meta,
        )
        return _unpack(reply)

    async def allreduce(self, tensor, op=ReduceOp.SUM):
        return await self._op("allreduce", np.asarray(tensor), op=op.value)

    async def reduce(self, tensor, root=0, op=ReduceOp.SUM):
        return await self._op("reduce", np.asarray(tensor), root=root, op=op.value)

    async def broadcast(self, tensor, root=0):
        return await self._op("broadcast", np.asarray(tensor), root=root)

    async def allgather(self, tensor):
        return await self._op("allgather", np.asarray(tensor))

    async def reducescatter(self, tensor, op=ReduceOp.SUM):
        return await self._op("reducescatter", np.asarray(tensor), op=op.value)

    async def barrier(self):
        await self._op("barrier", None)

    # ------------------------------------------------------- send / recv
    # Mailbox is a queue per (src, seq) so back-to-back sends with the
    # same tag enqueue rather than clobbering an already-resolved future.
    def _mail_queues(self, key):
        q = self._mailbox.get(key)
        if q is None:
            from collections import deque

            q = self._mailbox[key] = (deque(), deque())  # payloads, waiters
        return q

    async def _on_sendrecv(self, conn, src_rank: int, seq: int, payload: tuple):
        payloads, waiters = self._mail_queues((src_rank, seq))
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return {"ok": True}
        payloads.append(payload)
        return {"ok": True}

    async def send(self, tensor, dst_rank: int, seq: int = 0):
        reply = await self.core.head.call(
            "kv_get", key=f"collective:{self.name}:{dst_rank}"
        )
        if not reply["ok"]:
            raise rpc.RpcError(f"rank {dst_rank} not in group {self.name}")
        conn = await self.core._connect(reply["value"].decode())
        await conn.call(
            f"col_sendrecv:{self.name}",
            src_rank=self.rank,
            seq=seq,
            payload=_pack(np.asarray(tensor)),
        )

    async def recv(self, src_rank: int, seq: int = 0):
        payloads, waiters = self._mail_queues((src_rank, seq))
        if payloads:
            return _unpack(payloads.popleft())
        fut = asyncio.get_running_loop().create_future()
        waiters.append(fut)
        return _unpack(await fut)
