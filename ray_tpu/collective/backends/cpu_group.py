"""CPU collective backend: host tensors over the runtime RPC.

Fills the role of the reference's gloo backend (reference:
python/ray/util/collective/collective_group/torch_gloo_collective_group.py)
as the CPU baseline and test stand-in. Topology is hub-reduce: rank 0
collects contributions, reduces with numpy, and answers every member's
in-flight RPC with the result — one round trip per op, fine for control-
plane-sized tensors (accelerator tensors take the XLA backends).

Rendezvous replaces the reference's NCCLUniqueID named-actor store
(nccl_collective_group.py:29): members publish rank→addr in the head KV
and poll until the group is complete.

Fault tolerance (reference: NCCL abort + destroy_collective_group
semantics; "Efficient AllReduce with Stragglers" motivates the
telemetry):

- Every op and the rendezvous itself run under a deadline. The hub arms
  a timer when an op's first contribution arrives; expiry answers every
  waiting member with a structured timeout naming the missing ranks
  (raised member-side as CollectiveTimeoutError) and fire-and-forgets a
  head probe so a genuinely dead member is *confirmed* dead instead of
  timing out again next op.
- Members register with the head (addr + node addr + worker id). When
  the head declares a member dead — node heartbeat loss, worker reap,
  or a probe — it publishes on the "collective" channel; survivors
  poison the group and fail in-flight and future ops with
  CollectiveMemberDiedError immediately instead of burning the full
  timeout. The hub additionally watches member connections: a dropped
  conn aborts pending ops at once.
- A poisoned (or op-desynced) group is repaired by reform(): survivors
  re-rendezvous under a bumped epoch (fresh KV keys, fresh op
  sequence), re-ranked densely, with the lowest surviving rank as the
  new hub.
- Straggler telemetry: the hub records per-op first→last contribution
  lag and the slowest rank (util/metrics.py histogram + counter), so a
  chronically slow member is visible before it becomes a timeout.
- Partial K-of-N mode ("Efficient AllReduce with Stragglers",
  arXiv:2505.23523): ``allreduce/reducescatter/allgather(...,
  min_ranks=K, grace_s=...)`` arms a SECOND, earlier timer when the
  first contribution arrives. If the grace sub-deadline passes with ≥K
  contributions in hand (or the K-th lands after it), the hub completes
  the op over the contributors — SUM rescaled by world/K so downstream
  mean math stays correct; an allgather fills skipped slots with zeros —
  and answers everyone with typed PartialResult metadata naming the
  skipped ranks. A "partial" tombstone keeps the op's reply around so a
  straggler's late contribution is acked-and-discarded with the same
  result (it rejoins op-sequence-synchronized instead of hanging or
  desyncing). The hard deadline still raises CollectiveTimeoutError
  when even K never arrive. Skips feed the straggler stats, the
  ray_tpu_collective_partial_* metrics, and — past a sliding-window
  threshold — an escalation report to the head that triggers the
  chronic-straggler drain-and-replace path. The grace window itself is
  adaptive by default: once the hub has enough full-op lag samples, it
  derives grace from the straggler-lag histogram (p99 × 1.5, clamped)
  instead of the static config default.
- Compression (`EQuARX <https://arxiv.org/abs/2506.17615>`_):
  ``compression="int8"`` on allreduce/reducescatter/allgather ships
  block-scaled int8 + per-block fp32 absmax scales on the wire (~3.9×
  fewer bytes at block=256) while the hub dequantizes and ACCUMULATES
  IN FP32, requantizing only the reply — the codec is a wire format,
  never an accumulator. Measured wire bytes (the actual packed RPC
  payloads, both directions) feed the flight recorder's
  ray_tpu_collective_wire_bytes_total counter and compression-ratio
  gauge.
- Topology-aware algorithms ("The Big Send-off", arXiv:2504.18658):
  ``allreduce(..., algo=)`` can bypass the hub for a flat ring
  (bandwidth-optimal reduce-scatter + all-gather over the p2p mailbox)
  or a binomial tree (log2(n) latency terms — wins for small
  messages); ``algo="auto"`` picks by message size via the
  collective.algo crossover table. The default (None) keeps the hub
  path, byte-identical to before.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

import numpy as np

from ray_tpu._private import rpc
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu.collective import algo as colalgo
from ray_tpu.collective import codec
from ray_tpu.collective.flight_recorder import record_op, record_partial
from ray_tpu.collective.types import (
    CollectiveGroupDestroyedError,
    CollectiveMemberDiedError,
    CollectiveTimeoutError,
    PartialResult,
    ReduceOp,
)
from ray_tpu.util.metrics import Counter, Histogram

_REDUCERS = {
    ReduceOp.SUM: lambda xs: np.sum(xs, axis=0),
    ReduceOp.PRODUCT: lambda xs: np.prod(xs, axis=0),
    ReduceOp.MIN: lambda xs: np.min(xs, axis=0),
    ReduceOp.MAX: lambda xs: np.max(xs, axis=0),
}

# Pairwise combiners for the ring/tree p2p algorithms (streaming
# accumulation instead of the hub's stack-and-reduce).
_COMBINERS = {
    ReduceOp.SUM: np.add,
    ReduceOp.PRODUCT: np.multiply,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
}

# Ops that support partial K-of-N completion on the hub.
_PARTIAL_KINDS = frozenset({"allreduce", "reducescatter", "allgather"})

# Full-op lag samples needed before the adaptive grace window replaces
# the static default.
_ADAPTIVE_MIN_SAMPLES = 16

# Extra member-side wait beyond the hub's deadline: the hub answers
# expiry itself, so a member only hits its own backstop when the hub
# process is gone or wedged.
_HUB_GRACE_S = 5.0

_LAG_HIST = Histogram(
    "collective_straggler_lag_s",
    "first→last contribution spread per collective op (hub-measured)",
    boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
    tag_keys=("group", "op"),
)
_STRAGGLER_TOTAL = Counter(
    "collective_straggler_total",
    "ops in which this rank was the slowest (or missing) contributor",
    tag_keys=("group", "rank"),
)
_ABORT_TOTAL = Counter(
    "collective_abort_total",
    "collective ops aborted by timeout or member death",
    tag_keys=("group", "reason"),
)


class _Pending:
    __slots__ = ("contrib", "futures", "arrived", "started", "arrive_ts",
                 "timer", "grace_timer", "grace_passed", "min_ranks",
                 "grace_s", "meta")

    def __init__(self, world: int):
        self.contrib: list = [None] * world
        self.futures: list = []
        self.arrived = 0
        self.started = time.monotonic()
        self.arrive_ts: dict[int, float] = {}
        self.timer: asyncio.TimerHandle | None = None
        # Partial K-of-N state (None min_ranks = classic all-N op; the
        # partial path is never entered, byte-identical behavior).
        self.grace_timer: asyncio.TimerHandle | None = None
        self.grace_passed = False
        self.min_ranks: int | None = None
        self.grace_s: float = 0.0
        self.meta: dict = {}

    def cancel_timers(self):
        if self.timer is not None:
            self.timer.cancel()
        if self.grace_timer is not None:
            self.grace_timer.cancel()


def _pack(value) -> tuple[bytes, list[bytes]]:
    s = serialize(value).materialize_buffers()
    return s.inband, s.buffers


def _unpack(packed: tuple) -> Any:
    return deserialize(packed[0], packed[1])


def _packed_nbytes(packed: tuple) -> int:
    """Measured wire size of one packed payload (inband + oob buffers)."""
    inband, buffers = packed
    return len(inband) + sum(
        int(getattr(b, "nbytes", 0)) or len(b) for b in buffers
    )


def _compress(arr: np.ndarray, compression: str | None):
    """Payload → what goes on the wire (a codec dict when compressing)."""
    if compression is None:
        return arr
    from ray_tpu._private import config

    return codec.to_wire(
        codec.quantize(arr, block=config.get("COLLECTIVE_COMPRESSION_BLOCK"))
    )


def _decompress(value):
    """Inverse of :func:`_compress`, recursing into allgather lists."""
    if codec.is_wire(value):
        qt = codec.from_wire(value)
        return codec.dequantize(qt, dtype=qt.dtype)
    if isinstance(value, list):
        return [_decompress(v) for v in value]
    return value


def _contrib_array(value) -> np.ndarray:
    """A hub-side contribution as an fp32-accumulation-grade array
    (dequantizing codec payloads; raw arrays pass through)."""
    if codec.is_wire(value):
        return codec.dequantize(codec.from_wire(value))
    return np.asarray(value)


def _default_timeout() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_TIMEOUT_S")


def _default_partial_grace() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_PARTIAL_GRACE_S")


class CpuGroup:
    def __init__(
        self,
        core,
        group_name: str,
        world_size: int,
        rank: int,
        timeout_s: float | None = None,
        epoch: int = 0,
    ):
        self.core = core  # CoreWorker (for RPC + head KV)
        self.base_name = group_name
        self.epoch = epoch
        # Epoch-scoped internal name: a reformed group must never
        # rendezvous against (or serve ops for) a previous incarnation's
        # KV keys / handlers.
        self.name = group_name if epoch == 0 else f"{group_name}~e{epoch}"
        self.world = world_size
        self.rank = rank
        self.timeout_s = (
            _default_timeout() if timeout_s is None else float(timeout_s)
        )
        self.root_addr: str | None = None
        self._seq = 0
        self._pending: dict[tuple, _Pending] = {}  # (op_kind, seq) → state
        # (src, seq) → (deque[payload], deque[waiter futures])
        self._mailbox: dict[tuple, tuple] = {}
        self._dead: set[int] = set()      # ranks declared dead (poison)
        self._destroyed = False
        self._inflight: set[asyncio.Future] = set()  # member-side calls
        self._rank_conns: dict[int, rpc.Connection] = {}  # hub-side
        # straggler telemetry (hub-side): rank → times slowest
        self._straggler_counts: dict[int, int] = {}
        self._ops_completed = 0
        self._last_lag_s = 0.0
        # Partial-mode state (hub-side). _partial_done is the tombstone
        # cache: (kind, seq) → the completed op's reply, kept so a
        # straggler's LATE contribution is acked with the same partial
        # result instead of opening a fresh pending op that can only
        # time out. _skip_events is the sliding window feeding the
        # chronic-skip escalation to the head.
        self._partial_done: "dict[tuple, dict]" = {}
        self._partial_ops = 0
        self._skip_counts: dict[int, int] = {}
        self._skip_events: list[tuple[float, int]] = []
        self._skip_reported: set[int] = set()
        # Adaptive grace: sliding window of full-op first→last lag
        # samples (the straggler-lag histogram's raw feed); the hub
        # derives the partial grace window from its p99 once enough
        # samples exist.
        from collections import deque

        self._lag_samples: "deque[float]" = deque(maxlen=512)
        # Ring/tree p2p algorithm state: op counter for tag scoping and
        # a peer addr cache so each hop is one conn call, not a head KV
        # lookup per send.
        self._algo_seq = 0
        self._peer_addrs: dict[int, str] = {}
        if rank == 0:
            self.core.ext_handlers[f"col_op:{self.name}"] = self._on_op
        self.core.ext_handlers[f"col_sendrecv:{self.name}"] = self._on_sendrecv

    # --------------------------------------------------------- bootstrap
    async def init(self, timeout_s: float | None = None):
        """Rendezvous through the head KV, bounded by the group deadline:
        members that never join surface as CollectiveTimeoutError with
        the missing ranks, not an infinite poll loop."""
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + t
        key = f"collective:{self.name}:{self.rank}"
        await self.core.head.call("kv_put", key=key, value=self.core.addr.encode())
        # Membership registration: the head's table is what lets node /
        # worker death fan out to survivors as a typed abort.
        try:
            await self.core.head.call(
                "collective_register",
                group=self.base_name,
                rank=self.rank,
                epoch=self.epoch,
                addr=self.core.addr,
                node_addr=getattr(self.core, "node_addr", None),
                worker_id=getattr(self.core, "worker_id", None),
            )
        except rpc.RpcError:
            pass  # older head without the membership table: deadline
            # enforcement still works, only death fan-out is lost
        import ray_tpu.collective as _col

        await _col._ensure_death_watch(self.core)
        prefix = f"collective:{self.name}:"
        while True:
            reply = await self.core.head.call("kv_keys", prefix=prefix)
            present = set()
            for k in reply.get("keys", []):
                tail = k[len(prefix):]
                if tail.isdigit():
                    present.add(int(tail))
            if len(present & set(range(self.world))) == self.world:
                break
            if time.monotonic() > deadline:
                missing = sorted(set(range(self.world)) - present)
                await self._cleanup_rendezvous(key)
                raise CollectiveTimeoutError(
                    self.base_name, "rendezvous", t, missing_ranks=missing
                )
            await asyncio.sleep(0.05)
        reply = await self.core.head.call(
            "kv_get", key=f"{prefix}0"
        )
        self.root_addr = reply["value"].decode()

    async def _cleanup_rendezvous(self, key: str):
        """Failed init must not leave a half-registered member behind."""
        self.core.ext_handlers.pop(f"col_op:{self.name}", None)
        self.core.ext_handlers.pop(f"col_sendrecv:{self.name}", None)
        try:
            await self.core.head.call("kv_del", key=key)
            await self.core.head.call(
                "collective_deregister",
                group=self.base_name,
                epoch=self.epoch,
                rank=self.rank,
            )
        except rpc.RpcError:
            pass

    async def destroy(self, reason: str = "destroyed"):
        """Tear down AND fail everything in flight: hub-side pending op
        futures, member-side in-flight calls, and mailbox recv waiters —
        an awaiting coroutine must never stay pending past destroy.

        A tombstone handler replaces the hub's op endpoint so a straggler
        member's LATE op against this incarnation gets a typed answer
        (``reason`` of "reformed" lets auto_reform rejoin the new epoch)
        instead of an unknown-method RpcError."""
        self._destroyed = True

        async def _tombstone(conn, **kw):
            return {"ok": False, "error": reason}

        if self.rank == 0:
            self.core.ext_handlers[f"col_op:{self.name}"] = _tombstone
        self.core.ext_handlers.pop(f"col_sendrecv:{self.name}", None)
        for key, st in list(self._pending.items()):
            st.cancel_timers()
            for _rank, fut in st.futures:
                if not fut.done():
                    fut.set_result({"ok": False, "error": "destroyed"})
        self._pending.clear()
        self._partial_done.clear()
        for call in list(self._inflight):
            call.cancel()
        for payloads, waiters in self._mailbox.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_exception(
                        CollectiveGroupDestroyedError(self.base_name, "recv")
                    )
        self._mailbox.clear()
        try:
            await self.core.head.call(
                "collective_deregister",
                group=self.base_name,
                epoch=self.epoch,
                rank=self.rank,
            )
        except rpc.RpcError:
            pass
        if self.rank == 0:
            for r in range(self.world):
                try:
                    await self.core.head.call(
                        "kv_del", key=f"collective:{self.name}:{r}"
                    )
                except rpc.RpcError:
                    pass

    # ------------------------------------------------- abort-and-reform
    async def reform(self, timeout_s: float | None = None) -> "CpuGroup":
        """Re-run rendezvous with the surviving ranks under a bumped
        epoch: new dense ranks (order-preserving), new world size, the
        lowest surviving rank becomes the hub. Also repairs a desynced
        group after an op timeout (dead set empty → same shape, fresh
        op sequence)."""
        survivors = [r for r in range(self.world) if r not in self._dead]
        if self.rank not in survivors:
            raise CollectiveMemberDiedError(
                self.base_name,
                "reform",
                dead_ranks=sorted(self._dead),
                detail="this rank is itself marked dead",
            )
        g = CpuGroup(
            self.core,
            self.base_name,
            len(survivors),
            survivors.index(self.rank),
            timeout_s=self.timeout_s if timeout_s is None else timeout_s,
            epoch=self.epoch + 1,
        )
        await self.destroy(reason="reformed")
        await g.init()
        g.auto_reform = getattr(self, "auto_reform", False)
        return g

    # ------------------------------------------------ death propagation
    def _on_member_dead(self, ranks, epoch: int | None = None):
        """Head fan-out (or hub conn-loss) declared members dead: poison
        the group and abort everything in flight, now."""
        if self._destroyed:
            return
        if epoch is not None and epoch != self.epoch:
            return  # stale event about a previous incarnation
        dead = {int(r) for r in ranks} - {self.rank}
        if not dead or dead <= self._dead:
            return
        self._dead |= dead
        _ABORT_TOTAL.inc(
            tags={"group": self.base_name, "reason": "member_died"}
        )
        reply = {
            "ok": False,
            "error": "member_died",
            "dead_ranks": sorted(self._dead),
        }
        for key, st in list(self._pending.items()):
            st.cancel_timers()
            for _rank, fut in st.futures:
                if not fut.done():
                    fut.set_result(dict(reply))
        self._pending.clear()
        for call in list(self._inflight):
            call.cancel()
        err = CollectiveMemberDiedError(
            self.base_name, "recv", dead_ranks=sorted(self._dead)
        )
        for payloads, waiters in self._mailbox.values():
            for fut in waiters:
                if not fut.done():
                    fut.set_exception(err)

    def _watch_conn(self, rank: int, conn: rpc.Connection):
        """Hub-side: a member's dropped connection is a death signal —
        abort its group-mates' pending ops instead of waiting out the
        deadline (reference: NCCL comm abort on peer loss)."""
        if self._rank_conns.get(rank) is conn:
            return
        self._rank_conns[rank] = conn
        prev = conn.on_close

        def on_close(c, _prev=prev, _rank=rank):
            if _prev:
                _prev(c)
            if (
                not self._destroyed
                and self._rank_conns.get(_rank) is c
            ):
                self._on_member_dead([_rank])

        conn.on_close = on_close

    def _check_alive(self, op: str):
        if self._destroyed:
            raise CollectiveGroupDestroyedError(self.base_name, op)
        if self._dead:
            raise CollectiveMemberDiedError(
                self.base_name,
                op,
                dead_ranks=sorted(self._dead),
                detail="group is poisoned; reform_group() to continue",
            )

    def _probe_missing(self, ranks):
        """Fire-and-forget head probe: confirm whether silent ranks are
        dead so the next failure is a fast typed abort, and a dead node
        is reaped without waiting out HEALTH_TIMEOUT_S."""
        async def probe():
            try:
                await self.core.head.call(
                    "collective_probe",
                    group=self.base_name,
                    ranks=list(ranks),
                )
            except rpc.RpcError:
                pass

        asyncio.ensure_future(probe())

    # -------------------------------------------------------- hub (rank0)
    async def _on_op(
        self, conn, kind: str, seq: int, rank: int, payload: tuple, meta: dict
    ):
        if self._destroyed:
            return {"ok": False, "error": "destroyed"}
        if self._dead:
            return {
                "ok": False,
                "error": "member_died",
                "dead_ranks": sorted(self._dead),
            }
        key = (kind, seq)
        done = self._partial_done.get(key)
        if done is not None:
            # This op already partially completed without this rank:
            # ack-and-discard the late contribution, answering with the
            # SAME rescaled result + partial metadata (the straggler
            # rejoins typed and op-sequence-synchronized; a fresh
            # pending entry here could only hang until the deadline).
            # reducescatter tombstones are per-rank (each rank's chunk
            # differs); the other kinds share one reply.
            per_rank = done.get("per_rank")
            return per_rank[rank] if per_rank is not None else done["reply"]
        st = self._pending.get(key)
        if st is None:
            st = self._pending[key] = _Pending(self.world)
            timeout = float(meta.get("timeout_s") or self.timeout_s)
            loop = asyncio.get_running_loop()
            st.timer = loop.call_later(timeout, self._expire, key, timeout)
            min_ranks = meta.get("min_ranks")
            if min_ranks is not None and kind in _PARTIAL_KINDS:
                # Two-stage timer: the grace sub-deadline is measured
                # from the FASTEST arrival — which is this one, the
                # contribution that created the pending entry.
                st.min_ranks = max(1, min(int(min_ranks), self.world))
                st.grace_s = float(
                    meta.get("grace_s") or self._resolve_grace()
                )
                st.meta = dict(meta)
                st.grace_timer = loop.call_later(
                    st.grace_s, self._grace_fire, key
                )
        self._watch_conn(rank, conn)
        st.contrib[rank] = _unpack(payload)
        st.arrived += 1
        st.arrive_ts[rank] = time.monotonic()
        fut = asyncio.get_running_loop().create_future()
        st.futures.append((rank, fut))
        if st.arrived == self.world:
            st.cancel_timers()
            self._record_op_stats(kind, st)
            self._complete(key, st, kind, meta)
        elif (
            st.grace_passed
            and st.min_ranks is not None
            and st.arrived >= st.min_ranks
        ):
            # The K-th contribution landed after the grace sub-deadline:
            # proceed now rather than waiting out the hard deadline.
            self._complete_partial(key, st, kind, meta)
        return await fut

    def _grace_fire(self, key: tuple):
        """Grace sub-deadline: proceed with the K-of-N contributions in
        hand; with fewer than K, keep waiting (the K-th arrival or the
        hard deadline resolves the op)."""
        st = self._pending.get(key)
        if st is None:
            return
        st.grace_passed = True
        if st.min_ranks is not None and st.arrived >= st.min_ranks:
            self._complete_partial(key, st, key[0], st.meta)

    def _expire(self, key: tuple, timeout: float):
        """Hub deadline: answer every waiting member with the missing
        ranks, then probe them — a dead member becomes a confirmed
        death, a merely slow one shows up in the straggler stats."""
        st = self._pending.pop(key, None)
        if st is None:
            return
        st.cancel_timers()
        missing = [r for r in range(self.world) if st.contrib[r] is None]
        _ABORT_TOTAL.inc(tags={"group": self.base_name, "reason": "timeout"})
        for r in missing:
            self._straggler_counts[r] = self._straggler_counts.get(r, 0) + 1
            _STRAGGLER_TOTAL.inc(
                tags={"group": self.base_name, "rank": str(r)}
            )
        reply = {
            "ok": False,
            "error": "timeout",
            "missing_ranks": missing,
            "timeout_s": timeout,
            "op": key[0],
        }
        for _rank, fut in st.futures:
            if not fut.done():
                fut.set_result(dict(reply))
        self._probe_missing(missing)

    def _record_op_stats(self, kind: str, st: _Pending):
        self._ops_completed += 1
        if len(st.arrive_ts) < 2:
            return
        first = min(st.arrive_ts.values())
        last = max(st.arrive_ts.values())
        self._last_lag_s = last - first
        # Full-op arrivals only feed the adaptive-grace window: a
        # partial completion's spread is censored at the grace deadline
        # and would bias the p99 down.
        self._lag_samples.append(self._last_lag_s)
        slowest = max(st.arrive_ts, key=st.arrive_ts.get)
        self._straggler_counts[slowest] = (
            self._straggler_counts.get(slowest, 0) + 1
        )
        _LAG_HIST.observe(
            self._last_lag_s, tags={"group": self.base_name, "op": kind}
        )
        _STRAGGLER_TOTAL.inc(
            tags={"group": self.base_name, "rank": str(slowest)}
        )

    def _lag_p99(self) -> float | None:
        if len(self._lag_samples) < _ADAPTIVE_MIN_SAMPLES:
            return None
        return float(np.percentile(np.asarray(self._lag_samples), 99))

    def _resolve_grace(self) -> float:
        """Partial-mode grace window when the caller passed none: the
        straggler-lag histogram's p99 with 1.5x headroom, clamped to
        [COLLECTIVE_GRACE_MIN_S, COLLECTIVE_GRACE_MAX_S] — a group
        whose normal spread is 10ms stops waiting a full second for a
        straggler, and one whose spread is 2s is not strangled by the
        1s static default. Falls back to COLLECTIVE_PARTIAL_GRACE_S
        until enough full-op samples exist (or when the adaptive knob
        is off)."""
        from ray_tpu._private import config

        static = _default_partial_grace()
        if not config.get("COLLECTIVE_ADAPTIVE_GRACE"):
            return static
        p99 = self._lag_p99()
        if p99 is None:
            return static
        return float(
            min(
                max(p99 * 1.5, config.get("COLLECTIVE_GRACE_MIN_S")),
                config.get("COLLECTIVE_GRACE_MAX_S"),
            )
        )

    def straggler_stats(self) -> dict:
        """Hub-side per-rank slowest/missing counts (empty off-hub).
        ``partial_ops`` / ``skip_counts`` cover the K-of-N mode: how
        many ops completed without someone, and who got skipped.
        ``adaptive_grace_s`` is the grace window a partial op with no
        explicit grace_s would get right now; ``lag_p99_s`` the
        histogram percentile behind it (None until enough samples)."""
        return {
            "ops_completed": self._ops_completed,
            "last_lag_s": self._last_lag_s,
            "slowest_counts": dict(self._straggler_counts),
            "partial_ops": self._partial_ops,
            "skip_counts": dict(self._skip_counts),
            "lag_p99_s": self._lag_p99(),
            "adaptive_grace_s": self._resolve_grace(),
        }

    # -------------------------------------------- partial K-of-N (hub)
    def _complete_partial(self, key, st: _Pending, kind: str, meta: dict):
        """Complete an op over the K..N-1 contributions in hand: reduce
        the contributors (dequantized, fp32), rescale SUM by world/K (so
        result/world is the mean over actual contributors), answer every
        waiter with the result + partial metadata, and leave a tombstone
        reply for the stragglers' late contributions.

        Per kind: allreduce returns the rescaled reduction to everyone;
        reducescatter returns each rank ITS chunk of it (per-rank
        tombstones); allgather returns the contributed entries with
        zero-filled slots for the skipped ranks — the skip list, not the
        zeros, is the signal downstream code should branch on."""
        del self._pending[key]
        st.cancel_timers()
        compression = meta.get("compression")
        contributed = sorted(st.arrive_ts)
        skipped = [r for r in range(self.world) if st.contrib[r] is None]
        op = ReduceOp(meta.get("op", "sum"))
        self._partial_ops += 1
        self._ops_completed += 1
        record_partial(self.base_name, kind, skipped)
        now = time.monotonic()
        for r in skipped:
            self._skip_counts[r] = self._skip_counts.get(r, 0) + 1
            self._straggler_counts[r] = self._straggler_counts.get(r, 0) + 1
            _STRAGGLER_TOTAL.inc(
                tags={"group": self.base_name, "rank": str(r)}
            )
            self._skip_events.append((now, r))
        partial_meta = {
            "contributed": contributed,
            "skipped": skipped,
            "world": self.world,
        }
        done: dict
        if kind == "allgather":
            first = _contrib_array(st.contrib[contributed[0]])
            zero = np.zeros_like(first)
            entries = [
                st.contrib[r]
                if st.contrib[r] is not None
                else _compress(zero, compression)
                for r in range(self.world)
            ]
            reply = {
                "ok": True,
                "payload": _pack(entries),
                "partial": partial_meta,
            }
            done = {"reply": reply}
        else:
            stacked = np.stack(
                [_contrib_array(st.contrib[r]) for r in contributed]
            )
            result = _REDUCERS[op](stacked)
            if op is ReduceOp.SUM:
                result = result * (self.world / float(len(contributed)))
            if kind == "reducescatter":
                chunks = np.array_split(result, self.world, axis=0)
                per_rank = [
                    {
                        "ok": True,
                        "payload": _pack(_compress(c, compression)),
                        "partial": partial_meta,
                    }
                    for c in chunks
                ]
                done = {"per_rank": per_rank}
            else:
                reply = {
                    "ok": True,
                    "payload": _pack(_compress(result, compression)),
                    "partial": partial_meta,
                }
                done = {"reply": reply}
        for rank, fut in st.futures:
            if not fut.done():
                per_rank = done.get("per_rank")
                fut.set_result(
                    dict(per_rank[rank] if per_rank is not None
                         else done["reply"])
                )
        # Tombstone for the stragglers (bounded: ops complete in seq
        # order, old tombstones can no longer be asked for).
        self._partial_done[key] = done
        while len(self._partial_done) > 128:
            self._partial_done.pop(next(iter(self._partial_done)))
        self._escalate_chronic_skips(now)

    def _escalate_chronic_skips(self, now: float):
        """Report a rank whose skip count crossed the sliding-window
        threshold to the head — feeding the existing chronic-straggler
        drain-and-replace escalation (autoscaler straggler_drain) from
        inside the op instead of waiting on metric-snapshot latency."""
        from ray_tpu._private import config

        window = config.get("COLLECTIVE_SKIP_WINDOW_S")
        threshold = config.get("COLLECTIVE_SKIP_DRAIN_THRESHOLD")
        cutoff = now - window
        self._skip_events = [e for e in self._skip_events if e[0] >= cutoff]
        counts: dict[int, int] = {}
        for _ts, r in self._skip_events:
            counts[r] = counts.get(r, 0) + 1
        for r, n in counts.items():
            if n < threshold or r in self._skip_reported:
                continue
            self._skip_reported.add(r)

            async def report(rank=r, skips=n):
                try:
                    await self.core.head.call(
                        "collective_straggler_report",
                        group=self.base_name,
                        rank=rank,
                        skips=skips,
                        window_s=window,
                    )
                except rpc.RpcError:
                    pass  # older head: the metric-snapshot path still
                    # carries the signal, only the fast escalation is lost

            asyncio.ensure_future(report())

    def _complete(self, key, st: _Pending, kind: str, meta: dict):
        del self._pending[key]
        op = ReduceOp(meta.get("op", "sum"))
        compression = meta.get("compression")
        if compression is None:
            # Classic path: untouched numpy reduce over the raw
            # contributions — byte-identical to before the codec landed.
            contrib = st.contrib
        else:
            # Codec path: dequantize EVERY contribution and accumulate
            # in fp32; only the reply is requantized.
            contrib = [
                _contrib_array(c) if c is not None else None
                for c in st.contrib
            ]
        if kind == "allreduce" or kind == "reduce":
            result = _REDUCERS[op](np.stack(contrib))
            if compression is not None and kind == "allreduce":
                result = _compress(result, compression)
        elif kind == "allgather":
            # Compressed allgather passes the members' wire payloads
            # through untouched — nothing to reduce, nothing to requant.
            result = list(st.contrib)
        elif kind == "reducescatter":
            red = _REDUCERS[op](np.stack(contrib))
            result = [
                _compress(c, compression)
                for c in np.array_split(red, self.world, axis=0)
            ]
        elif kind == "broadcast":
            result = st.contrib[meta.get("root", 0)]
        elif kind == "barrier":
            result = None
        else:
            raise rpc.RpcError(f"unknown collective {kind}")
        for rank, fut in st.futures:
            if fut.done():
                continue
            if kind == "reducescatter":
                fut.set_result({"ok": True, "payload": _pack(result[rank])})
            elif kind == "reduce" and rank != meta.get("root", 0):
                fut.set_result({"ok": True, "payload": _pack(None)})
            else:
                fut.set_result({"ok": True, "payload": _pack(result)})

    # ----------------------------------------------------------- verbs
    def _interpret(self, kind: str, reply: dict):
        if reply.get("ok"):
            value = _unpack(reply["payload"]) if "payload" in reply else None
            partial = reply.get("partial")
            if partial is not None:
                return PartialResult(
                    value=value,
                    contributed=[int(r) for r in partial["contributed"]],
                    skipped=[int(r) for r in partial["skipped"]],
                    world=int(partial["world"]),
                )
            return value
        error = reply.get("error")
        if error == "timeout":
            raise CollectiveTimeoutError(
                self.base_name,
                kind,
                reply.get("timeout_s"),
                missing_ranks=reply.get("missing_ranks"),
            )
        if error in ("destroyed", "reformed"):
            raise CollectiveGroupDestroyedError(
                self.base_name,
                kind,
                detail="reformed" if error == "reformed" else "",
            )
        dead = [int(r) for r in reply.get("dead_ranks") or []]
        self._dead.update(d for d in dead if d != self.rank)
        raise CollectiveMemberDiedError(
            self.base_name, kind, dead_ranks=dead
        )

    async def _op(
        self, kind: str, tensor: Any, timeout_s: float | None = None, **meta
    ):
        self._check_alive(kind)
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        self._seq += 1
        seq = self._seq
        # Deterministic straggler injection (RAY_TPU_STRAGGLER_DELAY=
        # "rank:seconds,…"): the named ranks are late to every
        # contribution — the chaos knob the partial-collective and
        # straggler-stats tests are built on. Read per call so tests
        # can flip it at runtime; zero-cost when the spec is unset.
        from ray_tpu._private.test_utils import straggler_delay_for_rank

        delay = straggler_delay_for_rank(self.rank)
        if delay > 0:
            await asyncio.sleep(delay)
        wall_start = time.time()
        t0 = time.perf_counter()
        try:
            conn = await self.core._connect(self.root_addr)
        except rpc.ConnectionLost:
            self._dead.add(0)
            raise CollectiveMemberDiedError(
                self.base_name, kind, dead_ranks=[0],
                detail="cannot reach the hub rank",
            )
        # The packed RPC payloads are the ACTUAL wire bytes of this op:
        # measure them (both directions) for the flight recorder's wire
        # counter — the compression win shows up here, not in the
        # logical byte counter.
        packed = _pack(_compress(tensor, meta.get("compression"))
                       if tensor is not None else tensor)
        wire_sent = _packed_nbytes(packed)
        call = asyncio.ensure_future(
            conn.call(
                f"col_op:{self.name}",
                kind=kind,
                seq=seq,
                rank=self.rank,
                payload=packed,
                meta={**meta, "timeout_s": t},
            )
        )
        self._inflight.add(call)
        try:
            # The hub answers its own deadline; the grace-padded backstop
            # only fires when the hub process itself is gone or wedged.
            reply = await asyncio.wait_for(call, t + _HUB_GRACE_S)
        except asyncio.TimeoutError:
            self._probe_missing([0])
            raise CollectiveTimeoutError(
                self.base_name, kind, t,
                detail="hub rank 0 did not answer within the deadline",
            )
        except asyncio.CancelledError:
            # destroy() / death fan-out cancelled the in-flight call.
            if self._destroyed:
                raise CollectiveGroupDestroyedError(self.base_name, kind)
            if self._dead:
                raise CollectiveMemberDiedError(
                    self.base_name, kind, dead_ranks=sorted(self._dead)
                )
            raise
        except rpc.ConnectionLost:
            self._dead.add(0)
            raise CollectiveMemberDiedError(
                self.base_name, kind, dead_ranks=[0],
                detail="hub connection lost",
            )
        finally:
            self._inflight.discard(call)
        wire_recv = (
            _packed_nbytes(reply["payload"])
            if reply.get("ok") and "payload" in reply
            else 0
        )
        result = self._interpret(kind, reply)
        if meta.get("compression") is not None:
            if isinstance(result, PartialResult):
                result.value = _decompress(result.value)
            else:
                result = _decompress(result)
        record_op(
            self.base_name, kind, "cpu", self.world, tensor,
            wall_start, time.perf_counter() - t0,
            wire_bytes=wire_sent + wire_recv,
        )
        return result

    def _partial_meta(self, meta: dict, min_ranks, grace_s) -> dict:
        if min_ranks is not None:
            if not 1 <= int(min_ranks) <= self.world:
                raise ValueError(
                    f"min_ranks {min_ranks} out of range 1..{self.world}"
                )
            meta["min_ranks"] = int(min_ranks)
            if grace_s is not None:
                meta["grace_s"] = float(grace_s)
        return meta

    def _wrap_partial(self, out, min_ranks):
        if min_ranks is not None and not isinstance(out, PartialResult):
            # Everyone made the grace window: same typed envelope, no
            # skips — callers in partial mode always see PartialResult.
            out = PartialResult(
                value=out,
                contributed=list(range(self.world)),
                skipped=[],
                world=self.world,
            )
        return out

    def _resolve_algo(
        self, algo: str | None, nbytes: int, verb: str = "allreduce"
    ) -> str:
        """None → the hub (the default data plane, byte-identical to
        before algo= existed); "auto" → ring/tree by message size via
        the crossover table; explicit names pass through validated.

        For the reducescatter/allgather verbs (the ZeRO-sharded path's
        two hops) the latency-optimal plane IS the hub star — TREE maps
        to it — so "auto" routes small payloads through the hub and
        large ones onto the ring data plane."""
        if algo is None:
            return colalgo.HUB
        if algo == colalgo.AUTO:
            chosen = colalgo.choose_algorithm(nbytes, self.world, verb=verb)
        elif algo not in (colalgo.HUB, colalgo.RING, colalgo.TREE):
            raise ValueError(
                f"cpu backend supports algo hub/ring/tree/auto, "
                f"got {algo!r}"
            )
        else:
            chosen = algo
        if verb != "allreduce" and chosen == colalgo.TREE:
            return colalgo.HUB
        return chosen

    async def allreduce(
        self,
        tensor,
        op=ReduceOp.SUM,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
        compression: str | None = None,
        algo: str | None = None,
    ):
        """``min_ranks=K`` enables partial K-of-N mode: the hub proceeds
        once K contributions are in hand after ``grace_s`` past the
        fastest arrival, returning PartialResult metadata; with the
        default None the classic all-N path runs unchanged.

        ``compression="int8"`` ships block-scaled int8 on the wire
        (fp32 accumulation at the hub); ``algo=`` picks the data plane —
        hub (default), ring, tree, or auto (crossover by size)."""
        arr = np.asarray(tensor)
        compression = codec.check_codec(compression)
        chosen = self._resolve_algo(algo, arr.nbytes)
        if chosen in (colalgo.RING, colalgo.TREE) and self.world > 1:
            if min_ranks is not None:
                raise ValueError(
                    "partial mode (min_ranks=) requires the hub "
                    "algorithm: ring/tree have no central grace timer"
                )
            return await self._algo_allreduce(
                arr, op, chosen, timeout_s, compression
            )
        meta: dict = {"op": op.value}
        if compression is not None:
            meta["compression"] = compression
        self._partial_meta(meta, min_ranks, grace_s)
        out = await self._op("allreduce", arr, timeout_s=timeout_s, **meta)
        return self._wrap_partial(out, min_ranks)

    async def reduce(self, tensor, root=0, op=ReduceOp.SUM, timeout_s=None):
        return await self._op(
            "reduce", np.asarray(tensor), timeout_s=timeout_s,
            root=root, op=op.value,
        )

    async def broadcast(self, tensor, root=0, timeout_s=None):
        return await self._op(
            "broadcast", np.asarray(tensor), timeout_s=timeout_s, root=root
        )

    async def allgather(
        self,
        tensor,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
        compression: str | None = None,
        algo: str | None = None,
    ):
        """Partial mode (``min_ranks=K``) returns the gathered list with
        zero-filled entries for skipped ranks — the PartialResult's
        ``skipped`` list, not the zeros, is the authoritative signal.
        ``algo=`` picks the data plane (hub default; ring for large
        payloads under "auto" — the ZeRO allgather hop's routing)."""
        arr = np.asarray(tensor)
        compression = codec.check_codec(compression)
        chosen = self._resolve_algo(algo, arr.nbytes, "allgather")
        if chosen == colalgo.RING and self.world > 1:
            if min_ranks is not None:
                raise ValueError(
                    "partial mode (min_ranks=) requires the hub "
                    "algorithm: the ring has no central grace timer"
                )
            return await self._algo_scatter_gather(
                "allgather", arr, None, timeout_s, compression
            )
        meta: dict = {}
        if compression is not None:
            meta["compression"] = compression
        self._partial_meta(meta, min_ranks, grace_s)
        out = await self._op(
            "allgather", arr, timeout_s=timeout_s, **meta
        )
        return self._wrap_partial(out, min_ranks)

    async def reducescatter(
        self,
        tensor,
        op=ReduceOp.SUM,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
        compression: str | None = None,
        algo: str | None = None,
    ):
        """Partial mode rescales SUM by world/K like allreduce; each
        rank still receives its own chunk of the partial reduction.
        ``algo=`` picks the data plane (hub default; ring for large
        payloads under "auto" — the ZeRO reduce hop's routing)."""
        arr = np.asarray(tensor)
        compression = codec.check_codec(compression)
        chosen = self._resolve_algo(algo, arr.nbytes, "reducescatter")
        if chosen == colalgo.RING and self.world > 1:
            if min_ranks is not None:
                raise ValueError(
                    "partial mode (min_ranks=) requires the hub "
                    "algorithm: the ring has no central grace timer"
                )
            return await self._algo_scatter_gather(
                "reducescatter", arr, op, timeout_s, compression
            )
        meta: dict = {"op": op.value}
        if compression is not None:
            meta["compression"] = compression
        self._partial_meta(meta, min_ranks, grace_s)
        out = await self._op(
            "reducescatter", arr, timeout_s=timeout_s, **meta
        )
        return self._wrap_partial(out, min_ranks)

    async def barrier(self, timeout_s=None):
        await self._op("barrier", None, timeout_s=timeout_s)

    # ------------------------------------------- ring / tree algorithms
    # Flat-ring and binomial-tree allreduce over the p2p mailbox ("The
    # Big Send-off", arXiv:2504.18658): the ring moves 2(n-1)/n of the
    # payload per rank across 2(n-1) latency-bound steps
    # (bandwidth-optimal, wins for large messages); the tree moves the
    # full payload across ~2*log2(n) rounds (latency-optimal, wins
    # below the crossover size). Both compose with the int8 codec —
    # every hop quantizes its payload and accumulates in fp32 after
    # dequantizing.

    async def _algo_allreduce(
        self, arr, op, algo_name, timeout_s, compression
    ):
        self._check_alive("allreduce")
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        from ray_tpu._private.test_utils import straggler_delay_for_rank

        delay = straggler_delay_for_rank(self.rank)
        if delay > 0:
            await asyncio.sleep(delay)
        wall_start = time.time()
        t0 = time.perf_counter()
        self._algo_seq += 1
        tag_base = f"_{algo_name}{self._algo_seq}"
        wire = [0]
        run = (
            self._ring_allreduce if algo_name == colalgo.RING
            else self._tree_allreduce
        )
        try:
            result = await asyncio.wait_for(
                run(arr, op, tag_base, compression, wire), t
            )
        except asyncio.TimeoutError:
            missing = sorted(set(range(self.world)) - {self.rank})
            self._probe_missing(missing)
            raise CollectiveTimeoutError(
                self.base_name, "allreduce", t,
                detail=f"{algo_name} algorithm starved waiting on a peer "
                       "hop",
            )
        record_op(
            self.base_name, "allreduce", "cpu", self.world, arr,
            wall_start, time.perf_counter() - t0, wire_bytes=wire[0],
        )
        return result

    async def _algo_scatter_gather(
        self, verb, arr, op, timeout_s, compression
    ):
        """Shared driver for the ring reducescatter / allgather data
        planes (the two hops the ZeRO-sharded gradient path issues):
        deadline, straggler chaos, typed starvation, and honest
        measured wire bytes, mirroring :meth:`_algo_allreduce`."""
        self._check_alive(verb)
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        from ray_tpu._private.test_utils import straggler_delay_for_rank

        delay = straggler_delay_for_rank(self.rank)
        if delay > 0:
            await asyncio.sleep(delay)
        wall_start = time.time()
        t0 = time.perf_counter()
        self._algo_seq += 1
        tag_base = f"_{colalgo.RING}{verb[0]}{self._algo_seq}"
        wire = [0]
        run = (
            self._ring_reducescatter(arr, op, tag_base, compression, wire)
            if verb == "reducescatter"
            else self._ring_allgather(arr, tag_base, compression, wire)
        )
        try:
            result = await asyncio.wait_for(run, t)
        except asyncio.TimeoutError:
            missing = sorted(set(range(self.world)) - {self.rank})
            self._probe_missing(missing)
            raise CollectiveTimeoutError(
                self.base_name, verb, t,
                detail="ring algorithm starved waiting on a peer hop",
            )
        record_op(
            self.base_name, verb, "cpu", self.world, arr,
            wall_start, time.perf_counter() - t0, wire_bytes=wire[0],
        )
        return result

    async def _ring_reducescatter(
        self, arr, op, tag_base, compression, wire
    ):
        """Ring reduce-scatter: the first phase of the ring allreduce —
        n-1 hops, each shipping one 1/n chunk — after which rank r holds
        the fully reduced chunk r (matching the hub's
        ``np.array_split(result, world, axis=0)[r]`` contract)."""
        n, r = self.world, self.rank
        combine = _COMBINERS[op]
        acc_dtype = np.float32 if compression is not None else arr.dtype
        chunks = [
            np.asarray(c, acc_dtype)
            for c in np.array_split(np.asarray(arr), n, axis=0)
        ]
        right, left = (r + 1) % n, (r - 1) % n
        # After hop s, chunk (r-s-1) mod n holds the running reduction
        # of s+2 ranks; n-1 hops leave rank r owning chunk r's total
        # (the classic schedule ends at (r+1) mod n — start one step
        # earlier so ownership lands on r itself).
        for s in range(n - 1):
            send_idx = (r - s - 1) % n
            recv_idx = (r - s - 2) % n
            got = await self._exchange(
                right, left, f"{tag_base}:rs{s}", chunks[send_idx],
                compression, wire,
            )
            chunks[recv_idx] = combine(
                chunks[recv_idx], np.asarray(got, acc_dtype)
            )
        return chunks[r].astype(arr.dtype, copy=False)

    async def _ring_allgather(self, arr, tag_base, compression, wire):
        """Ring all-gather: n-1 hops, each forwarding the chunk received
        on the previous hop; returns the per-rank list (the hub
        allgather contract). Chunk shapes may differ per rank (the
        array_split remainder) — the mailbox ships arrays, not fixed
        frames, so unequal hops are fine."""
        n, r = self.world, self.rank
        entries: list = [None] * n
        entries[r] = np.asarray(arr)
        right, left = (r + 1) % n, (r - 1) % n
        cur = entries[r]
        for s in range(n - 1):
            got = await self._exchange(
                right, left, f"{tag_base}:ag{s}", cur, compression, wire
            )
            cur = np.asarray(got, entries[r].dtype)
            entries[(r - s - 1) % n] = cur
        return entries

    async def _exchange(self, dst, src, tag, value, compression, wire):
        """One algorithm hop: send ``value`` to ``dst`` while receiving
        the same-tagged payload from ``src``; returns the received
        array (dequantized when the codec is on)."""
        packed = _pack(_compress(value, compression))
        wire[0] += _packed_nbytes(packed)

        async def _send():
            conn = await self.core._connect(await self._peer_addr(dst))
            await conn.call(
                f"col_sendrecv:{self.name}",
                src_rank=self.rank,
                seq=tag,
                payload=packed,
            )

        send_task = asyncio.ensure_future(_send())
        try:
            got = await self._p2p_recv(src, tag, wire)
        except BaseException:
            # Cancelled/timed out mid-hop: do not let the finally-await
            # of a wedged send block the cancellation itself.
            send_task.cancel()
            raise
        await send_task
        return got

    async def _peer_addr(self, rank: int) -> str:
        addr = self._peer_addrs.get(rank)
        if addr is None:
            reply = await self.core.head.call(
                "kv_get", key=f"collective:{self.name}:{rank}"
            )
            if not reply.get("ok"):
                raise CollectiveMemberDiedError(
                    self.base_name, "allreduce", dead_ranks=[rank],
                    detail=f"rank {rank} left the rendezvous KV",
                )
            addr = reply["value"].decode()
            self._peer_addrs[rank] = addr
        return addr

    async def _p2p_recv(self, src: int, tag, wire):
        payloads, waiters = self._mail_queues((src, tag))
        if payloads:
            packed = payloads.popleft()
        else:
            fut = asyncio.get_running_loop().create_future()
            waiters.append(fut)
            packed = await fut  # outer wait_for bounds the whole op
        # Algo tags are single-use: drop the drained queue entry so a
        # long-lived group does not leak one mailbox slot per hop.
        if not payloads and not waiters:
            self._mailbox.pop((src, tag), None)
        wire[0] += _packed_nbytes(packed)
        got = _unpack(packed)
        if codec.is_wire(got):
            return codec.dequantize(codec.from_wire(got))
        return got

    async def _ring_allreduce(self, arr, op, tag_base, compression, wire):
        """Flat ring: reduce-scatter (n-1 hops, each 1/n of the
        payload) then all-gather (n-1 more). After hop s of the first
        phase, chunk (rank-s-1) mod n holds the running reduction of
        s+2 ranks; rank r ends owning the fully reduced chunk
        (r+1) mod n."""
        n, r = self.world, self.rank
        combine = _COMBINERS[op]
        acc_dtype = np.float32 if compression is not None else arr.dtype
        flat = np.asarray(arr, acc_dtype).reshape(-1)
        length = flat.size
        chunk_len = max(1, -(-length // n))
        padded = np.zeros(n * chunk_len, acc_dtype)
        padded[:length] = flat
        chunks = [
            padded[i * chunk_len:(i + 1) * chunk_len].copy()
            for i in range(n)
        ]
        right, left = (r + 1) % n, (r - 1) % n
        for s in range(n - 1):
            send_idx = (r - s) % n
            recv_idx = (r - s - 1) % n
            got = await self._exchange(
                right, left, f"{tag_base}:rs{s}", chunks[send_idx],
                compression, wire,
            )
            chunks[recv_idx] = combine(
                chunks[recv_idx], np.asarray(got, acc_dtype)
            )
        for s in range(n - 1):
            send_idx = (r + 1 - s) % n
            recv_idx = (r - s) % n
            got = await self._exchange(
                right, left, f"{tag_base}:ag{s}", chunks[send_idx],
                compression, wire,
            )
            chunks[recv_idx] = np.asarray(got, acc_dtype)
        out = np.concatenate(chunks)[:length].reshape(arr.shape)
        return out.astype(arr.dtype, copy=False)

    async def _tree_allreduce(self, arr, op, tag_base, compression, wire):
        """Binomial tree rooted at rank 0: reduce up (children with
        lowbit m send to parent r-m), broadcast the result back down —
        2*ceil(log2(n)) full-payload rounds, exponentially fewer
        latency terms than the ring."""
        n, r = self.world, self.rank
        combine = _COMBINERS[op]
        acc_dtype = np.float32 if compression is not None else arr.dtype
        val = np.asarray(arr, acc_dtype).copy()
        maxmask = 1 << max(0, (n - 1).bit_length())
        lowbit = (r & -r) if r else maxmask
        # Reduce: receive from my children (r+m for m < lowbit),
        # smallest subtree first, then send the subtotal to my parent.
        m = 1
        while m < lowbit:
            child = r + m
            if child < n:
                got = await self._p2p_recv(child, f"{tag_base}:r{m}", wire)
                val = combine(val, np.asarray(got, acc_dtype))
            m <<= 1
        if r != 0:
            packed = _pack(_compress(val, compression))
            wire[0] += _packed_nbytes(packed)
            conn = await self.core._connect(await self._peer_addr(r - lowbit))
            await conn.call(
                f"col_sendrecv:{self.name}",
                src_rank=self.rank,
                seq=f"{tag_base}:r{lowbit}",
                payload=packed,
            )
            # Broadcast: the reduced total comes back from the parent.
            got = await self._p2p_recv(
                r - lowbit, f"{tag_base}:b{lowbit}", wire
            )
            val = np.asarray(got, acc_dtype)
        # Relay down to my children, largest subtree first.
        m = lowbit >> 1
        while m >= 1:
            child = r + m
            if child < n:
                packed = _pack(_compress(val, compression))
                wire[0] += _packed_nbytes(packed)
                conn = await self.core._connect(await self._peer_addr(child))
                await conn.call(
                    f"col_sendrecv:{self.name}",
                    src_rank=self.rank,
                    seq=f"{tag_base}:b{m}",
                    payload=packed,
                )
            m >>= 1
        return np.asarray(val, acc_dtype).reshape(arr.shape).astype(
            arr.dtype, copy=False
        )

    # ------------------------------------------------------- send / recv
    # Mailbox is a queue per (src, seq) so back-to-back sends with the
    # same tag enqueue rather than clobbering an already-resolved future.
    def _mail_queues(self, key):
        q = self._mailbox.get(key)
        if q is None:
            from collections import deque

            q = self._mailbox[key] = (deque(), deque())  # payloads, waiters
        return q

    async def _on_sendrecv(self, conn, src_rank: int, seq: int, payload: tuple):
        payloads, waiters = self._mail_queues((src_rank, seq))
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return {"ok": True}
        payloads.append(payload)
        return {"ok": True}

    async def send(self, tensor, dst_rank: int, seq: int = 0, timeout_s=None):
        self._check_alive("send")
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        arr = np.asarray(tensor)
        wall_start = time.time()
        t0 = time.perf_counter()

        async def _send():
            reply = await self.core.head.call(
                "kv_get", key=f"collective:{self.name}:{dst_rank}"
            )
            if not reply["ok"]:
                raise rpc.RpcError(
                    f"rank {dst_rank} not in group {self.name}"
                )
            conn = await self.core._connect(reply["value"].decode())
            await conn.call(
                f"col_sendrecv:{self.name}",
                src_rank=self.rank,
                seq=seq,
                payload=_pack(arr),
            )

        try:
            await asyncio.wait_for(_send(), t)
        except asyncio.TimeoutError:
            raise CollectiveTimeoutError(
                self.base_name, "send", t, missing_ranks=[dst_rank]
            )
        record_op(
            self.base_name, "send", "cpu", self.world, arr,
            wall_start, time.perf_counter() - t0,
        )

    async def recv(self, src_rank: int, seq: int = 0, timeout_s=None):
        self._check_alive("recv")
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        wall_start = time.time()
        t0 = time.perf_counter()
        payloads, waiters = self._mail_queues((src_rank, seq))
        if payloads:
            result = _unpack(payloads.popleft())
        else:
            fut = asyncio.get_running_loop().create_future()
            waiters.append(fut)
            try:
                result = _unpack(await asyncio.wait_for(fut, t))
            except asyncio.TimeoutError:
                raise CollectiveTimeoutError(
                    self.base_name, "recv", t, missing_ranks=[src_rank]
                )
        record_op(
            self.base_name, "recv", "cpu", self.world, result,
            wall_start, time.perf_counter() - t0,
        )
        return result
