"""XLA collective backends: compiled ICI/DCN collectives behind eager verbs.

Replaces the reference's NCCL backend (reference:
python/ray/util/collective/collective_group/nccl_collective_group.py).
On TPU there is no user-level NCCL-like library: collectives are XLA ops
compiled into programs and scheduled on the ICI. The eager verbs here are
therefore *cached compiled programs* — one jit per (op, shape, dtype,
group) with donated inputs — which is the TPU-native answer to
"allreduce(tensor) must be fast" (SURVEY.md section 5, comm-backend row).

Two flavors:
  XlaMeshGroup — the group is a set of devices visible to this process
      ("ranks" = devices, SPMD single-controller).
  bootstrap_distributed — multi-host: ranks are processes; coordinator
      rendezvous via the head KV replaces the NCCLUniqueID named-actor
      store; after jax.distributed.initialize the same compiled-verb
      machinery works over ICI + DCN.
"""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from functools import partial
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

from ray_tpu.collective import algo as colalgo
from ray_tpu.collective import codec
from ray_tpu.collective.flight_recorder import record_op, record_partial
from ray_tpu.collective.types import (
    CollectiveMemberDiedError,
    CollectiveTimeoutError,
    CollectiveWork,
    FutureCollectiveWork,
    PartialResult,
    ReduceOp,
)

_PSUM_OPS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


def _default_timeout() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_TIMEOUT_S")


def _default_partial_grace() -> float:
    from ray_tpu._private import config

    return config.get("COLLECTIVE_PARTIAL_GRACE_S")


def _check_partial_args(op, dtype, min_ranks, world):
    """Partial mode on the XLA backends is a masked psum: SUM only
    (min/max/product have no meaningful zero-weight identity under the
    rescale) over inexact dtypes (the mask multiply and world/K rescale
    are float ops)."""
    if op is not ReduceOp.SUM:
        raise ValueError(
            f"partial allreduce supports ReduceOp.SUM only, got {op}"
        )
    if not jnp.issubdtype(dtype, jnp.inexact):
        raise TypeError(
            f"partial allreduce needs a floating dtype, got {dtype}"
        )
    if min_ranks is not None and not 1 <= int(min_ranks) <= world:
        raise ValueError(
            f"min_ranks {min_ranks} out of range 1..{world}"
        )


class _RecordStateMixin:
    """Per-THREAD flight-recorder state: the reentrancy flag and the
    analytic wire-byte drop box. Thread-local because the async
    dispatch thread runs verbs concurrently with the issuing thread —
    a shared flag would let one thread's in-flight op suppress or
    clobber the other's recording."""

    @property
    def _in_recorded_op(self) -> bool:
        return getattr(self._rec_tl, "flag", False)

    @_in_recorded_op.setter
    def _in_recorded_op(self, v: bool) -> None:
        self._rec_tl.flag = v

    @property
    def _last_wire_bytes(self):
        return getattr(self._rec_tl, "wire", None)

    @_last_wire_bytes.setter
    def _last_wire_bytes(self, v) -> None:
        self._rec_tl.wire = v


def _recorded(verb: str):
    """Flight-recorder wrapper for an eager verb: latency + bytes +
    bus-bandwidth metrics and a timeline SPAN on success. Reentrancy-
    guarded per group — verbs that lower onto other verbs (reduce →
    allreduce, barrier → allreduce, non-sum reducescatter) record only
    the outermost call."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kw):
            if self._in_recorded_op:
                return fn(self, *args, **kw)
            self._in_recorded_op = True
            # Ops whose transfers run inside a compiled program (the
            # codec / algo paths) deposit their analytic wire-byte
            # count here; None keeps the legacy convention.
            self._last_wire_bytes = None
            wall_start = time.time()
            t0 = time.perf_counter()
            try:
                out = fn(self, *args, **kw)
            finally:
                self._in_recorded_op = False
            record_op(
                self.name, verb, self.backend_tag, self.world,
                args[0] if args else None,
                wall_start, time.perf_counter() - t0,
                wire_bytes=self._last_wire_bytes,
            )
            return out

        return wrapper

    return deco


def _compressed_allreduce_fn(world: int, length: int, block: int):
    """Build the shard_map body of the EQuARX-style compressed
    allreduce: quantize the local payload into ``world`` block-aligned
    chunks → all_to_all the int8 chunks + scales (each rank collects
    chunk i of every peer) → dequantize and ACCUMULATE IN FP32 →
    rescale by world/Σw (partial-mode mask) → requantize the reduced
    chunk → all_gather int8 back → dequantize. Bytes crossing the
    interconnect are int8 + 1/block fp32 scales, ~3.9x fewer than f32,
    while the compiled shape never depends on the data or the mask."""
    import jax

    chunk_len = codec.padded_len(-(-max(1, length) // world), block)
    total = world * chunk_len
    nblk = chunk_len // block

    def fn(s, w):
        x = s[0].astype(jnp.float32) * w[0]
        flat = jnp.pad(x.reshape(-1), (0, total - length))
        blocks = flat.reshape(world, nblk, block)
        q, scales = codec.quantize_blocked_jax(blocks)
        q_t = jax.lax.all_to_all(
            q, "ranks", split_axis=0, concat_axis=0, tiled=True
        )
        s_t = jax.lax.all_to_all(
            scales, "ranks", split_axis=0, concat_axis=0, tiled=True
        )
        deq = q_t.astype(jnp.float32) * s_t[..., None]
        red = jnp.sum(deq, axis=0)  # (nblk, block) — fp32 accumulate
        cnt = jax.lax.psum(w[0], "ranks")
        red = red * (world / jnp.maximum(cnt, 1.0))
        q2, scales2 = codec.quantize_blocked_jax(red)
        qg = jax.lax.all_gather(q2, "ranks", axis=0, tiled=False)
        sg = jax.lax.all_gather(scales2, "ranks", axis=0, tiled=False)
        out = (qg.astype(jnp.float32) * sg[..., None]).reshape(-1)
        mask = jax.lax.all_gather(w[0], "ranks")
        return out[:length].reshape(s[0].shape)[None], mask[None]

    return fn


def _compressed_wire_bytes(world: int, length: int, block: int) -> int:
    """Per-rank analytic wire bytes of the compressed allreduce: the
    all_to_all and the all_gather each move (n-1)/n of the quantized
    payload (int8 data + fp32 scales)."""
    chunk_len = codec.padded_len(-(-max(1, length) // world), block)
    payload = world * (chunk_len + (chunk_len // block) * 4)
    return int(2 * (world - 1) / world * payload)


def _ring_allreduce_fn(world: int, length: int):
    """Bandwidth-optimal decomposition: psum_scatter + all_gather (the
    'ring' lowering) instead of the one-shot psum XLA typically lowers
    as a latency-optimized tree — the algo= selector's large-message
    choice."""
    import jax

    padded = -(-max(1, length) // world) * world

    def fn(s):
        flat = jnp.pad(s[0].reshape(-1), (0, padded - length))
        shard = jax.lax.psum_scatter(
            flat, "ranks", scatter_dimension=0, tiled=True
        )
        full = jax.lax.all_gather(shard, "ranks", axis=0, tiled=True)
        return full[:length].reshape(s[0].shape)[None]

    return fn


def _compression_block() -> int:
    from ray_tpu._private import config

    return int(config.get("COLLECTIVE_COMPRESSION_BLOCK"))


class XlaCollectiveWork(CollectiveWork):
    """Async handle over XLA's asynchronous dispatch: the compiled
    program is already launched when the handle exists, and the handle
    OWNS the result device buffers — ``wait()`` blocks until they are
    ready (``jax.block_until_ready``) and returns the same value the
    synchronous verb would have. The flight-recorder entry is written
    once, at completion, with the dispatch→completion wall interval and
    the op's analytic wire bytes, so overlapped device time is
    attributed honestly instead of as a near-zero dispatch blip."""

    __slots__ = ("_xgroup", "_out", "_wall_start", "_t0", "_wire_bytes",
                 "_payload")

    def __init__(self, group, verb, out, wall_start, t0, wire_bytes,
                 payload):
        super().__init__(group_name=group.name, verb=verb)
        self._xgroup = group
        self._out = out
        self._wall_start = wall_start
        self._t0 = t0
        self._wire_bytes = wire_bytes
        self._payload = payload

    def _leaves(self) -> list:
        val = (
            self._out.value
            if isinstance(self._out, PartialResult)
            else self._out
        )
        return list(val) if isinstance(val, (list, tuple)) else [val]

    def _join(self, timeout_s):
        # In-process device programs complete or raise — there is no
        # remote member to wait on, so the local deadline is moot
        # (API parity with the process-backed handles).
        del timeout_s
        jax.block_until_ready(self._leaves())
        record_op(
            self._xgroup.name, self.verb, self._xgroup.backend_tag,
            self._xgroup.world, self._payload, self._wall_start,
            time.perf_counter() - self._t0,
            wire_bytes=self._wire_bytes,
        )
        return self._out

    def _probe(self) -> bool:
        try:
            return all(
                leaf.is_ready()
                for leaf in self._leaves()
                if hasattr(leaf, "is_ready")
            )
        # tpulint: allow(broad-except reason=is_ready probing across jax versions/array types; a probe failure means "treat as ready" so wait() resolves it definitively)
        except Exception:
            return True


class XlaMeshGroup(_RecordStateMixin):
    """Eager collectives over the devices visible to this process.

    Single-controller semantics: every verb takes a *sequence* of
    world_size per-rank tensors (rank = device) and returns the per-rank
    results."""

    expects_per_rank_tensors = True
    backend_tag = "xla_mesh"

    def __init__(
        self,
        devices: Sequence[jax.Device] | None = None,
        name: str = "xla_mesh",
    ):
        self.devices = list(devices if devices is not None else jax.devices())
        self.world = len(self.devices)
        self.name = name
        self.mesh = Mesh(np.array(self.devices), ("ranks",))
        self._programs: dict[tuple, Any] = {}
        self._rec_tl = threading.local()

    # ------------------------------------------------------------ plumbing
    def _stack(self, tensors: Sequence[Any]) -> jax.Array:
        """Per-rank tensors → one global array sharded on 'ranks'."""
        if len(tensors) != self.world:
            raise ValueError(
                f"expected {self.world} per-rank tensors, got {len(tensors)}"
            )
        sharding = NamedSharding(self.mesh, P("ranks"))
        arrs = [jnp.asarray(t)[None] for t in tensors]
        return jax.make_array_from_single_device_arrays(
            (self.world, *arrs[0].shape[1:]),
            sharding,
            [jax.device_put(a, d) for a, d in zip(arrs, self.devices)],
        )

    def _unstack(self, stacked: jax.Array) -> list[jax.Array]:
        return [s.data[0] for s in stacked.addressable_shards]

    def _program(self, key: tuple, build):
        prog = self._programs.get(key)
        if prog is None:
            prog = self._programs[key] = build()
        return prog

    def _shmap(self, fn, donate=True):
        mapped = shard_map(
            fn, mesh=self.mesh, in_specs=P("ranks"), out_specs=P("ranks")
        )
        return jax.jit(mapped, donate_argnums=(0,) if donate else ())

    # ------------------------------------------------------------- verbs
    # timeout_s is accepted for API parity with the fault-tolerant
    # backends: in-process device collectives either complete or raise —
    # there is no remote member to wait on.
    @_recorded("allreduce")
    def allreduce(
        self,
        tensors: Sequence[Any],
        op=ReduceOp.SUM,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s=None,
        skip_ranks: Sequence[int] | None = None,
        compression: str | None = None,
        algo: str | None = None,
    ) -> list:
        del timeout_s, grace_s
        if codec.check_codec(compression) is not None:
            # Compressed path subsumes partial: the mask rides the same
            # compiled program (weight-0 contributions, world/Σw
            # rescale) so the two compose without a second variant.
            return self._compressed_allreduce(
                tensors, op, min_ranks, skip_ranks
            )
        if min_ranks is not None or skip_ranks:
            # Single-controller partial mode: local devices cannot
            # straggle on the wire, so the "slow" set is EXPLICIT —
            # ranks flagged by drain notices / external straggler
            # telemetry mask to weight 0 in a compiled psum whose shape
            # never changes (the T3-style integration point).
            return self._partial_allreduce(
                tensors, op, min_ranks, skip_ranks
            )
        if algo is not None:
            chosen = self._choose_algo(algo, tensors, op)
            if chosen == colalgo.RING:
                return self._ring_allreduce(tensors)
        x = self._stack(tensors)
        key = ("allreduce", x.shape, str(x.dtype), op)
        if op is ReduceOp.PRODUCT:
            # No pprod primitive: exp∘psum∘log is wrong for negatives, so
            # run an all_gather and reduce locally.
            prog = self._program(
                key,
                lambda: self._shmap(
                    lambda s: jnp.prod(
                        jax.lax.all_gather(s, "ranks", axis=0), axis=(0, 1)
                    )[None]
                ),
            )
        else:
            psum = _PSUM_OPS[op]
            prog = self._program(
                key, lambda: self._shmap(lambda s: psum(s, "ranks"))
            )
        return self._unstack(prog(x))

    def _partial_allreduce(
        self, tensors, op, min_ranks, skip_ranks
    ) -> PartialResult:
        """Masked psum: contribution r is multiplied by weight w_r
        (0 for skipped ranks) and the sum rescaled by world / Σw, so
        result/world equals the mean over actual contributors. One
        cached compiled program per (shape, dtype) — the mask is an
        input, not a shape."""
        x = self._stack(tensors)
        _check_partial_args(op, x.dtype, min_ranks, self.world)
        skipped = sorted({int(r) for r in (skip_ranks or ())})
        contributed = [r for r in range(self.world) if r not in skipped]
        if len(contributed) < int(min_ranks or 1):
            raise CollectiveTimeoutError(
                self.name,
                "allreduce",
                None,
                missing_ranks=skipped,
                detail=f"masking left {len(contributed)} contributors, "
                       f"below min_ranks {min_ranks}",
            )
        world = self.world
        key = ("partial_allreduce", x.shape, str(x.dtype))

        def build():
            def fn(s, w):
                wb = w.reshape((1,) + (1,) * (s.ndim - 1))
                tot = jax.lax.psum(s * wb, "ranks")
                cnt = jax.lax.psum(w, "ranks")
                return tot * (world / jnp.maximum(cnt, 1.0))

            mapped = shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=P("ranks"),
            )
            return jax.jit(mapped)

        prog = self._program(key, build)
        w = np.ones((world,), dtype=x.dtype)
        w[skipped] = 0
        out = self._unstack(prog(x, jnp.asarray(w)))
        if skipped:
            record_partial(self.name, "allreduce", skipped)
        return PartialResult(
            value=out, contributed=contributed, skipped=skipped, world=world
        )

    def _choose_algo(self, algo: str, tensors, op) -> str:
        """Resolve algo= for the compiled backends: "tree" keeps the
        one-shot psum (XLA's latency-optimized lowering), "ring" lowers
        to psum_scatter + all_gather (bandwidth-optimal), "auto" picks
        by per-rank message size via the crossover table; a multi-slice
        device set under "auto" routes to the hierarchical two-level
        op."""
        first = tensors[0] if tensors else None
        nbytes = int(getattr(np.asarray(first), "nbytes", 0)) if (
            first is not None
        ) else 0
        n_slices = len(
            {getattr(d, "slice_index", 0) for d in self.devices}
        )
        chosen = colalgo.choose_algorithm(
            nbytes, self.world, n_slices=n_slices, override=algo
        )
        if chosen == colalgo.HUB:
            raise ValueError(
                "the hub algorithm is a cpu-backend data plane; "
                "compiled backends take tree/ring/auto"
            )
        if chosen == colalgo.RING and op is not ReduceOp.SUM:
            # psum_scatter has no min/max/product form; the one-shot
            # lowering already handles those.
            return colalgo.TREE
        return chosen

    def _ring_allreduce(self, tensors: Sequence[Any]) -> list:
        x = self._stack(tensors)
        length = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        key = ("ring_allreduce", x.shape, str(x.dtype))
        prog = self._program(
            key,
            lambda: self._shmap(_ring_allreduce_fn(self.world, length)),
        )
        self._last_wire_bytes = colalgo.wire_bytes_per_rank(
            colalgo.RING, length * x.dtype.itemsize, self.world
        )
        return self._unstack(prog(x))

    def _compressed_allreduce(
        self, tensors, op, min_ranks, skip_ranks
    ):
        """Block-scaled int8 allreduce compiled around all_to_all /
        all_gather (quantize → exchange int8 → fp32 accumulate →
        requantize → gather). Composes with partial mode: skip_ranks
        mask to weight 0 inside the same program."""
        x = self._stack(tensors)
        if op is not ReduceOp.SUM:
            raise ValueError(
                f"compressed allreduce supports ReduceOp.SUM only, "
                f"got {op}"
            )
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            raise TypeError(
                f"compressed allreduce needs a floating dtype, got "
                f"{x.dtype}"
            )
        partial = min_ranks is not None or bool(skip_ranks)
        skipped = sorted({int(r) for r in (skip_ranks or ())})
        contributed = [r for r in range(self.world) if r not in skipped]
        if partial and len(contributed) < int(min_ranks or 1):
            raise CollectiveTimeoutError(
                self.name,
                "allreduce",
                None,
                missing_ranks=skipped,
                detail=f"masking left {len(contributed)} contributors, "
                       f"below min_ranks {min_ranks}",
            )
        block = _compression_block()
        length = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        key = ("q8_allreduce", x.shape, str(x.dtype), block)

        def build():
            mapped = shard_map(
                _compressed_allreduce_fn(self.world, length, block),
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=(P("ranks"), P("ranks")),
            )
            return jax.jit(mapped)

        prog = self._program(key, build)
        w = np.ones((self.world,), dtype=np.float32)
        w[skipped] = 0
        out, _mask = prog(
            x, self._stack_weights(jnp.asarray(w, x.dtype))
        )
        result = self._unstack(out)
        self._last_wire_bytes = _compressed_wire_bytes(
            self.world, length, block
        )
        if not partial:
            return result
        if skipped:
            record_partial(self.name, "allreduce", skipped)
        return PartialResult(
            value=result, contributed=contributed, skipped=skipped,
            world=self.world,
        )

    def _stack_weights(self, w):
        """Per-rank scalar weights → a (world,) array sharded on
        'ranks' (the mask input of the compressed program)."""
        from jax.sharding import NamedSharding

        sharding = NamedSharding(self.mesh, P("ranks"))
        return jax.device_put(w, sharding)

    # ------------------------------------------------------ async verbs
    def _verb_async(self, verb: str, args, kw) -> CollectiveWork:
        """Dispatch a verb through XLA's async dispatch and hand back a
        handle owning the result buffers. The synchronous verb body only
        *launches* compiled programs (unstacking reads shard handles,
        not host values), so calling it here returns at dispatch; the
        flight-recorder entry moves to the handle's completion."""
        wall_start = time.time()
        t0 = time.perf_counter()
        prev = self._in_recorded_op
        self._in_recorded_op = True  # the handle records, not the verb
        if not prev:
            self._last_wire_bytes = None
        try:
            out = getattr(self, verb)(*args, **kw)
        finally:
            self._in_recorded_op = prev
        return XlaCollectiveWork(
            self, verb, out, wall_start, t0, self._last_wire_bytes,
            args[0] if args else None,
        )

    def allreduce_async(self, tensors: Sequence[Any], **kw) -> CollectiveWork:
        """Async :meth:`allreduce`: returns a :class:`CollectiveWork`
        immediately; composes with every sync kwarg (op/min_ranks/
        skip_ranks/compression/algo) — partial mode resolves its mask at
        dispatch (the skip set is explicit on this backend), so
        ``wait()`` returns the same PartialResult envelope."""
        return self._verb_async("allreduce", (tensors,), kw)

    def reducescatter_async(
        self, tensors: Sequence[Any], **kw
    ) -> CollectiveWork:
        return self._verb_async("reducescatter", (tensors,), kw)

    def allgather_async(self, tensors: Sequence[Any], **kw) -> CollectiveWork:
        return self._verb_async("allgather", (tensors,), kw)

    @_recorded("broadcast")
    def broadcast(
        self, tensors: Sequence[Any], root: int = 0, timeout_s=None
    ) -> list:
        del timeout_s
        src = jnp.asarray(tensors[root])
        return [jax.device_put(src, d) for d in self.devices]

    @_recorded("allgather")
    def allgather(
        self, tensors: Sequence[Any], timeout_s=None,
        compression: str | None = None,
        algo: str | None = None,
    ) -> list:
        del timeout_s
        x = self._stack(tensors)
        # all_gather has one compiled lowering (ring on ICI); algo= is
        # accepted for selector parity and prices the wire honestly.
        del algo
        if codec.check_codec(compression) is not None:
            return self._compressed_allgather(x)
        key = ("allgather", x.shape, str(x.dtype))
        prog = self._program(
            key,
            lambda: self._shmap(
                # s is [1, ...] (this rank's slice); gather the unstacked
                # tensors tiled along their first data axis.
                lambda s: jax.lax.all_gather(s[0], "ranks", axis=0, tiled=True)[
                    None
                ],
                donate=False,
            ),
        )
        nbytes = int(np.prod(x.shape[1:]) * x.dtype.itemsize) if (
            x.ndim > 1
        ) else x.dtype.itemsize
        self._last_wire_bytes = colalgo.wire_bytes_per_rank(
            colalgo.RING, nbytes, self.world, verb="allgather"
        )
        return self._unstack(prog(x))

    def _compressed_allgather(self, x) -> list:
        """Quantize the local payload → all_gather int8 + scales →
        dequantize: the gather's wire traffic is the compressed size."""
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            raise TypeError(
                f"compressed allgather needs a floating dtype, got "
                f"{x.dtype}"
            )
        block = _compression_block()
        world = self.world
        shape = x.shape[1:]
        length = int(np.prod(shape)) if shape else 1
        padded = codec.padded_len(length, block)
        key = ("q8_allgather", x.shape, str(x.dtype), block)

        def build():
            def fn(s):
                flat = jnp.pad(
                    s[0].astype(jnp.float32).reshape(-1),
                    (0, padded - length),
                )
                q, scales = codec.quantize_blocked_jax(
                    flat.reshape(-1, block)
                )
                qg = jax.lax.all_gather(q, "ranks", axis=0, tiled=False)
                sg = jax.lax.all_gather(
                    scales, "ranks", axis=0, tiled=False
                )
                deq = (qg.astype(jnp.float32) * sg[..., None]).reshape(
                    world, -1
                )[:, :length]
                return deq.reshape(world, *shape).reshape(
                    world * shape[0] if shape else world, *shape[1:]
                )[None].astype(s.dtype)

            return self._shmap(fn, donate=False)

        prog = self._program(key, build)
        q_payload = padded + (padded // block) * 4
        self._last_wire_bytes = int(
            (world - 1) / world * world * q_payload
        )
        return self._unstack(prog(x))

    @_recorded("reducescatter")
    def reducescatter(
        self, tensors: Sequence[Any], op=ReduceOp.SUM, timeout_s=None,
        compression: str | None = None,
        algo: str | None = None,
        min_ranks: int | None = None,
        grace_s=None,
        skip_ranks: Sequence[int] | None = None,
    ) -> list:
        del timeout_s, grace_s
        x = self._stack(tensors)
        if x.shape[1] % self.world:
            raise ValueError(
                f"reducescatter dim0 {x.shape[1]} not divisible by world "
                f"{self.world}"
            )
        nbytes = int(np.prod(x.shape[1:]) * x.dtype.itemsize) if (
            x.ndim > 1
        ) else x.dtype.itemsize
        if codec.check_codec(compression) is not None:
            if op is not ReduceOp.SUM:
                raise ValueError(
                    "compressed reducescatter supports ReduceOp.SUM only"
                )
            if min_ranks is not None or skip_ranks:
                raise ValueError(
                    "compressed reducescatter does not compose with "
                    "partial mode yet: drop min_ranks/skip_ranks or "
                    "compression"
                )
            return self._compressed_reducescatter(x)
        if min_ranks is not None or skip_ranks:
            # Partial K-of-N on the reduce hop (the ZeRO reduce-scatter
            # composes with allow_partial_grads): masked psum_scatter —
            # skipped ranks contribute weight 0, SUM rescaled world/Σw.
            return self._partial_reducescatter(
                x, op, min_ranks, skip_ranks
            )
        if op is ReduceOp.SUM:
            chosen = colalgo.RING
            if algo is not None:
                chosen = colalgo.choose_algorithm(
                    nbytes, self.world, override=algo,
                    verb="reducescatter",
                )
            if chosen == colalgo.TREE:
                # Latency-optimal one-shot: full psum, keep our slice.
                # The small-payload branch of the selector — one
                # compiled reduction instead of n-1 scatter hops.
                key = ("rs_tree", x.shape, str(x.dtype))
                chunk = x.shape[1] // self.world

                def build():
                    def fn(s):
                        full = jax.lax.psum(s, "ranks")
                        idx = jax.lax.axis_index("ranks")
                        return jax.lax.dynamic_slice_in_dim(
                            full[0], idx * chunk, chunk, axis=0
                        )[None]

                    return self._shmap(fn)

                prog = self._program(key, build)
                self._last_wire_bytes = colalgo.wire_bytes_per_rank(
                    colalgo.TREE, nbytes, self.world,
                    verb="reducescatter",
                )
                return self._unstack(prog(x))
            key = ("reducescatter", x.shape, str(x.dtype), op)
            psum_scatter = partial(jax.lax.psum_scatter, axis_name="ranks")
            prog = self._program(
                key,
                lambda: self._shmap(
                    lambda s: psum_scatter(
                        s[0], scatter_dimension=0, tiled=True
                    )[None]
                ),
            )
            self._last_wire_bytes = colalgo.wire_bytes_per_rank(
                colalgo.RING, nbytes, self.world, verb="reducescatter"
            )
            return self._unstack(prog(x))
        # Non-sum reductions: reduce via the matching allreduce, then each
        # rank keeps its slice (no fused primitive for max/min/product).
        reduced = self.allreduce(tensors, op=op)
        chunk = reduced[0].shape[0] // self.world
        return [
            r[i * chunk : (i + 1) * chunk] for i, r in enumerate(reduced)
        ]

    def _partial_reducescatter(
        self, x, op, min_ranks, skip_ranks
    ) -> PartialResult:
        """Masked psum_scatter: contribution r is weighted w_r (0 for
        skipped ranks), the scattered SUM rescaled by world/Σw — the
        same semantics as :meth:`_partial_allreduce` applied to the
        ZeRO reduce hop. The gather hop never runs partial (a skipped
        OWNER would zero its weight shard, not merely degrade it)."""
        _check_partial_args(op, x.dtype, min_ranks, self.world)
        skipped = sorted({int(r) for r in (skip_ranks or ())})
        contributed = [r for r in range(self.world) if r not in skipped]
        if len(contributed) < int(min_ranks or 1):
            raise CollectiveTimeoutError(
                self.name,
                "reducescatter",
                None,
                missing_ranks=skipped,
                detail=f"masking left {len(contributed)} contributors, "
                       f"below min_ranks {min_ranks}",
            )
        world = self.world
        key = ("partial_reducescatter", x.shape, str(x.dtype))

        def build():
            def fn(s, w):
                wb = w.reshape((1,) + (1,) * (s.ndim - 1))
                shard = jax.lax.psum_scatter(
                    (s * wb)[0], "ranks", scatter_dimension=0, tiled=True
                )
                cnt = jax.lax.psum(w, "ranks")
                return (shard * (world / jnp.maximum(cnt, 1.0)))[None]

            mapped = shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=P("ranks"),
            )
            return jax.jit(mapped)

        prog = self._program(key, build)
        w = np.ones((world,), dtype=x.dtype)
        w[skipped] = 0
        out = self._unstack(prog(x, jnp.asarray(w)))
        if skipped:
            record_partial(self.name, "reducescatter", skipped)
        return PartialResult(
            value=out, contributed=contributed, skipped=skipped, world=world
        )

    def _compressed_reducescatter(self, x) -> list:
        """Quantized chunks → all_to_all int8 → fp32 dequant-accumulate:
        each rank ends with its fully reduced slice, having moved only
        int8 on the wire (the first half of the compressed allreduce —
        no requantize, the result never travels again)."""
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            raise TypeError(
                f"compressed reducescatter needs a floating dtype, got "
                f"{x.dtype}"
            )
        block = _compression_block()
        world = self.world
        shape = x.shape[1:]
        chunk_shape = (shape[0] // world, *shape[1:])
        clen = int(np.prod(chunk_shape)) if chunk_shape else 1
        padded = codec.padded_len(clen, block)
        key = ("q8_reducescatter", x.shape, str(x.dtype), block)

        def build():
            def fn(s):
                v = s[0].astype(jnp.float32).reshape(world, clen)
                v = jnp.pad(v, ((0, 0), (0, padded - clen)))
                q, scales = codec.quantize_blocked_jax(
                    v.reshape(world, -1, block)
                )
                q_t = jax.lax.all_to_all(
                    q, "ranks", split_axis=0, concat_axis=0, tiled=True
                )
                s_t = jax.lax.all_to_all(
                    scales, "ranks", split_axis=0, concat_axis=0,
                    tiled=True,
                )
                deq = q_t.astype(jnp.float32) * s_t[..., None]
                red = jnp.sum(deq, axis=0).reshape(-1)[:clen]
                return red.reshape(chunk_shape)[None].astype(s.dtype)

            return self._shmap(fn)

        prog = self._program(key, build)
        q_payload = world * (padded + (padded // block) * 4)
        self._last_wire_bytes = int((world - 1) / world * q_payload)
        return self._unstack(prog(x))

    @_recorded("permute")
    def permute(self, tensors: Sequence[Any], perm: list[tuple[int, int]]):
        """collective_permute: the P2P primitive TPU channels are built on
        (replaces NCCL send/recv, reference: nccl_group.py)."""
        x = self._stack(tensors)
        key = ("permute", x.shape, str(x.dtype), tuple(perm))
        prog = self._program(
            key,
            lambda: self._shmap(
                lambda s: jax.lax.ppermute(s, "ranks", perm=perm)
            ),
        )
        return self._unstack(prog(x))

    @_recorded("reduce")
    def reduce(
        self, tensors: Sequence[Any], root: int = 0, op=ReduceOp.SUM,
        timeout_s=None,
    ):
        """Single-controller semantics: returns the reduced tensor (the
        'root' distinction is process-level and meaningless in-process)."""
        del root, timeout_s
        return self.allreduce(tensors, op=op)

    def send(self, *a, **kw):
        raise NotImplementedError(
            "xla_mesh is single-controller: point-to-point movement is "
            "`permute` (collective_permute over ICI), not send/recv"
        )

    recv = send

    @_recorded("barrier")
    def barrier(self, timeout_s=None):
        del timeout_s
        ones = [jnp.zeros((), jnp.int32) for _ in range(self.world)]
        self.allreduce(ones)


class XlaDistGroup(_RecordStateMixin):
    """Multi-host eager collectives: rank = process, data over ICI + DCN.

    Standard multi-host JAX pattern: every process calls the same verb
    with *its own* tensor; the global array is assembled from addressable
    shards only (jax.make_array_from_single_device_arrays), and the
    compiled psum runs SPMD across all hosts. Requires
    jax.distributed.initialize first (see bootstrap_distributed).
    Tested with real process boundaries on a multi-process CPU cluster
    (tests/test_multihost.py, gloo CPU collectives); on TPU pods the
    same code runs over ICI/DCN.
    """

    expects_per_rank_tensors = False
    backend_tag = "xla_dist"

    def __init__(
        self,
        world_size: int,
        rank: int,
        timeout_s: float | None = None,
        name: str = "xla_dist",
        core=None,
    ):
        self.world = world_size
        self.rank = rank
        self.name = name
        self.base_name = name
        self.epoch = 0
        self.core = core  # CoreWorker, for head membership deregistration
        self._rec_tl = threading.local()
        # Poison state, fed by the head's death fan-out (see
        # _on_member_dead): the deadline-bounded sync polls this BETWEEN
        # bounded waits, so a fan-out interrupts a wedged compiled
        # collective well before its deadline, not at it.
        self._dead: set[int] = set()
        self.timeout_s = (
            _default_timeout() if timeout_s is None else float(timeout_s)
        )
        by_proc: dict[int, jax.Device] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) != world_size:
            raise ValueError(
                f"jax.distributed reports {len(by_proc)} processes, "
                f"expected {world_size}"
            )
        self.devices = [by_proc[p] for p in sorted(by_proc)]
        self.my_device = by_proc[jax.process_index()]
        self.mesh = Mesh(np.array(self.devices), ("ranks",))
        self._programs: dict[tuple, Any] = {}
        self._sync_pool: Any = None  # lazy single-thread deadline pool
        # Lazy single-thread async-dispatch pool: one thread keeps the
        # issue order of handle-based ops identical across ranks (a
        # reordered collective is a deadlock on a real mesh).
        self._dispatch_pool: Any = None
        self._gate_seq = 0  # partial-mode pre-op gate sequence
        self._last_wire_bytes: int | None = None

    def _global(self, tensor) -> jax.Array:
        local = jax.device_put(jnp.asarray(tensor)[None], self.my_device)
        sharding = NamedSharding(self.mesh, P("ranks"))
        return jax.make_array_from_single_device_arrays(
            (self.world, *local.shape[1:]), sharding, [local]
        )

    def _local(self, arr: jax.Array):
        return arr.addressable_shards[0].data[0]

    def _run(self, key, fn, x):
        prog = self._programs.get(key)
        if prog is None:
            mapped = shard_map(
                fn, mesh=self.mesh, in_specs=P("ranks"), out_specs=P("ranks")
            )
            prog = self._programs[key] = jax.jit(mapped)
        return prog(x)

    def _on_member_dead(self, ranks, epoch: int | None = None):
        """Head fan-out declared members dead: poison the group. The
        sync loop (and every future op's entry check) turns this into a
        typed abort — there is no comm handle to cancel on XLA, but the
        waiting THREAD can stop waiting immediately."""
        if epoch is not None and epoch != self.epoch:
            return
        self._dead.update(
            int(r) for r in (ranks or []) if int(r) != self.rank
        )

    def _check_poisoned(self, op: str):
        if self._dead:
            raise CollectiveMemberDiedError(
                self.name,
                op,
                dead_ranks=sorted(self._dead),
                detail="re-init jax.distributed to recover",
            )

    async def destroy(self):
        """Deregister from the head's membership table and release the
        sync pool; the jax.distributed runtime itself has no per-group
        teardown (re-init covers reform). Queued-but-unstarted async
        dispatches are cancelled — their handles fail typed
        (CollectiveGroupDestroyedError) instead of hanging."""
        if self._sync_pool is not None:
            self._sync_pool.shutdown(wait=False)
            self._sync_pool = None
        if self._dispatch_pool is not None:
            self._dispatch_pool.shutdown(wait=False, cancel_futures=True)
            self._dispatch_pool = None
        if self.core is not None:
            try:
                await self.core.head.call(
                    "collective_deregister",
                    group=self.base_name,
                    epoch=self.epoch,
                    rank=self.rank,
                )
            # tpulint: allow(broad-except reason=deregistration during teardown; the head may already be gone and the membership table reaps dead members anyway)
            except Exception:
                pass

    _POISON_POLL_S = 0.25

    def _sync(self, arr: jax.Array, op: str, timeout_s) -> jax.Array:
        """Deadline-bounded device sync. A peer process dying mid-op
        leaves the compiled collective blocked inside the runtime with
        no abort handle (the NCCL-comm-abort gap on XLA); waiting on a
        side thread turns that silent hang into a typed
        CollectiveTimeoutError. Between bounded waits the loop polls the
        group's poison flag, so a head death fan-out aborts the wait as
        soon as it arrives instead of at the deadline. The wedged thread
        is abandoned — the caller is expected to tear down / reform via
        jax.distributed re-init, matching destroy-and-reform
        semantics."""
        t = self.timeout_s if timeout_s is None else float(timeout_s)
        if not t or t <= 0:
            return jax.block_until_ready(arr)
        if self._sync_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._sync_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="xla_col_sync"
            )
        from concurrent.futures import TimeoutError as _FutTimeout

        fut = self._sync_pool.submit(jax.block_until_ready, arr)
        deadline = time.monotonic() + t
        while True:
            if self._dead:
                # Abandon the wedged wait NOW — the fan-out beat the
                # deadline. Fresh pool for the post-reform op.
                self._sync_pool = None
                self._check_poisoned(op)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._sync_pool = None
                raise CollectiveTimeoutError(
                    "xla_dist", op, t,
                    detail="compiled collective never completed (peer "
                           "process lost?); re-init jax.distributed to "
                           "recover",
                )
            try:
                return fut.result(min(self._POISON_POLL_S, remaining))
            except _FutTimeout:
                continue

    @_recorded("allreduce")
    def allreduce(
        self,
        tensor,
        op=ReduceOp.SUM,
        timeout_s=None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
        compression: str | None = None,
        algo: str | None = None,
    ):
        self._check_poisoned("allreduce")
        if codec.check_codec(compression) is not None:
            return self._compressed_allreduce_dist(
                tensor, op, min_ranks, grace_s, timeout_s
            )
        if min_ranks is not None:
            return self._partial_allreduce(
                tensor, op, min_ranks, grace_s, timeout_s
            )
        x = self._global(tensor)
        if algo is not None:
            chosen = colalgo.choose_algorithm(
                int(np.asarray(tensor).nbytes), self.world,
                override=algo,
            )
            if chosen == colalgo.RING and op is ReduceOp.SUM:
                length = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
                out = self._run(
                    ("ring_allreduce", x.shape, str(x.dtype)),
                    _ring_allreduce_fn(self.world, length),
                    x,
                )
                self._last_wire_bytes = colalgo.wire_bytes_per_rank(
                    colalgo.RING, length * x.dtype.itemsize, self.world
                )
                return self._local(self._sync(out, "allreduce", timeout_s))
        psum = _PSUM_OPS[op]
        out = self._run(
            ("allreduce", x.shape, str(x.dtype), op),
            lambda s: psum(s, "ranks"),
            x,
        )
        return self._local(self._sync(out, "allreduce", timeout_s))

    @staticmethod
    def _coord_client():
        """The jax coordination-service KV client, when
        jax.distributed is initialized in this process (None
        otherwise). The gate prefers it over head-KV round trips: the
        coordination service is the same fault-domain as the compiled
        op itself — a head restart, head-KV latency spike, or RPC
        retry can no longer mis-price a contribution."""
        try:
            from jax._src import distributed as _dist

            return _dist.global_state.client
        # tpulint: allow(broad-except reason=jax internals moved or distributed never initialized; the gate falls back to head-KV)
        except Exception:
            return None

    def _coord_gate_open_ts(self, key: str, now: float) -> float | None:
        """First-arrival timestamp via the jax coordination service:
        every rank races one ``key_value_set`` (first writer wins;
        losers raise on the duplicate key) then reads the winner with a
        bounded blocking get — after our own set attempt the value
        exists, so the bound only matters during service teardown.
        Returns None when the service is unavailable (head-KV fallback
        applies)."""
        client = self._coord_client()
        if client is None:
            return None
        try:
            try:
                client.key_value_set(key, repr(now))
            # tpulint: allow(broad-except reason=another rank won the first-writer set race; the bounded get below returns the winner)
            except Exception:
                pass
            return float(client.blocking_key_value_get(key, 2000))
        # tpulint: allow(broad-except reason=coordination service mid-teardown or pre-init; gate falls back to head-KV pricing)
        except Exception:
            return None

    def _gate_weight(self, grace_s: float) -> float:
        """Pre-op bounded barrier, self-flagging: the first rank to
        reach the op claims a gate-open timestamp; a rank arriving more
        than ``grace_s`` later contributes with weight 0. Each rank
        owns only ITS OWN weight, so clock skew or races can never make
        the compiled psum's inputs inconsistent — a mis-decided rank
        merely includes/excludes itself. No waiting happens here: the
        compiled op is the synchronization point, the gate only prices
        the contribution.

        The claim goes through the jax COORDINATION SERVICE when
        jax.distributed is initialized (the ROADMAP follow-up: the gate
        lives in the same fault domain as the op, not behind head-KV
        wall clocks); the head KV remains the fallback for processes
        without a coordination client."""
        self._gate_seq += 1
        key = f"pgate:{self.name}:{self._gate_seq}"
        now = time.time()
        open_ts = self._coord_gate_open_ts(key, now)
        if open_ts is not None:
            return 0.0 if (now - open_ts) > grace_s else 1.0
        if self.core is None:
            return 1.0

        async def claim():
            reply = await self.core.head.call("kv_get", key=key)
            if reply.get("ok"):
                return float(reply["value"].decode())
            await self.core.head.call("kv_put", key=key, value=str(now).encode())
            if self._gate_seq > 1 and self.rank == 0:
                # Best-effort GC of the previous op's gate key. A
                # straggler still on that seq just re-claims it and
                # self-prices at weight 1 — the safe direction.
                await self.core.head.call(
                    "kv_del", key=f"pgate:{self.name}:{self._gate_seq - 1}"
                )
            return now

        try:
            import ray_tpu.api as _api

            open_ts = _api._runtime.run(claim())
        except Exception as e:  # noqa: BLE001 - gate is advisory
            import logging

            logger = logging.getLogger("ray_tpu.collective")
            logger.debug(
                "partial gate unavailable (%s): contributing at weight 1",
                e,
            )
            return 1.0
        return 0.0 if (now - open_ts) > grace_s else 1.0

    def _partial_allreduce(self, tensor, op, min_ranks, grace_s, timeout_s):
        """Masked psum over ICI/DCN: every rank contributes
        ``(grad * w, w)`` where w∈{0,1} comes from the pre-op gate, so
        the compiled op's shape never changes whoever straggles. The
        gathered weight mask doubles as the skipped-rank metadata, and
        the rescale world/Σw happens inside the compiled program."""
        grace = (
            float(grace_s) if grace_s is not None
            else _default_partial_grace()
        )
        x = self._global(tensor)
        _check_partial_args(op, x.dtype, min_ranks, self.world)
        w_self = self._gate_weight(grace)
        w = self._global(jnp.asarray(w_self, x.dtype))
        world = self.world
        key = ("partial_allreduce", x.shape, str(x.dtype))
        prog = self._programs.get(key)
        if prog is None:

            def fn(s, wv):
                wb = wv.reshape((1,) + (1,) * (s.ndim - 1))
                tot = jax.lax.psum(s * wb, "ranks")
                cnt = jax.lax.psum(wv, "ranks")
                mask = jax.lax.all_gather(wv[0], "ranks")
                return tot * (world / jnp.maximum(cnt, 1.0)), mask[None]

            mapped = shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=(P("ranks"), P("ranks")),
            )
            prog = self._programs[key] = jax.jit(mapped)
        out, mask = prog(x, w)
        out = self._local(self._sync(out, "allreduce", timeout_s))
        maskv = np.asarray(self._local(mask))
        contributed = [r for r in range(world) if maskv[r] > 0]
        skipped = [r for r in range(world) if maskv[r] <= 0]
        if len(contributed) < int(min_ranks):
            raise CollectiveTimeoutError(
                self.name,
                "allreduce",
                grace,
                missing_ranks=skipped,
                detail=f"only {len(contributed)} contributions beat the "
                       f"partial grace window, below min_ranks {min_ranks}",
            )
        if skipped and self.rank == 0:
            record_partial(self.name, "allreduce", skipped)
        return PartialResult(
            value=out, contributed=contributed, skipped=skipped, world=world
        )

    def _partial_reducescatter_dist(
        self, tensor, op, min_ranks, grace_s, timeout_s
    ):
        """Masked psum_scatter over ICI/DCN — the ZeRO reduce hop under
        allow_partial_grads on the multi-process backend: the same
        pre-op gate as :meth:`_partial_allreduce` prices each rank's
        contribution (w∈{0,1}), the scattered SUM rescales by
        world/Σw inside the compiled program, and the gather hop
        stays all-N (a skipped OWNER would zero weight shards)."""
        grace = (
            float(grace_s) if grace_s is not None
            else _default_partial_grace()
        )
        x = self._global(tensor)
        if x.shape[1] % self.world:
            raise ValueError(
                f"reducescatter dim0 {x.shape[1]} not divisible by "
                f"world {self.world}"
            )
        _check_partial_args(op, x.dtype, min_ranks, self.world)
        w_self = self._gate_weight(grace)
        w = self._global(jnp.asarray(w_self, x.dtype))
        world = self.world
        key = ("partial_reducescatter", x.shape, str(x.dtype))
        prog = self._programs.get(key)
        if prog is None:

            def fn(s, wv):
                wb = wv.reshape((1,) + (1,) * (s.ndim - 1))
                shard = jax.lax.psum_scatter(
                    (s * wb)[0], "ranks", scatter_dimension=0,
                    tiled=True,
                )
                cnt = jax.lax.psum(wv, "ranks")
                mask = jax.lax.all_gather(wv[0], "ranks")
                return (
                    (shard * (world / jnp.maximum(cnt, 1.0)))[None],
                    mask[None],
                )

            mapped = shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=(P("ranks"), P("ranks")),
            )
            prog = self._programs[key] = jax.jit(mapped)
        out, mask = prog(x, w)
        out = self._local(self._sync(out, "reducescatter", timeout_s))
        maskv = np.asarray(self._local(mask))
        contributed = [r for r in range(world) if maskv[r] > 0]
        skipped = [r for r in range(world) if maskv[r] <= 0]
        if len(contributed) < int(min_ranks):
            raise CollectiveTimeoutError(
                self.name,
                "reducescatter",
                grace,
                missing_ranks=skipped,
                detail=f"only {len(contributed)} contributions beat the "
                       f"partial grace window, below min_ranks {min_ranks}",
            )
        if skipped and self.rank == 0:
            record_partial(self.name, "reducescatter", skipped)
        return PartialResult(
            value=out, contributed=contributed, skipped=skipped, world=world
        )

    def _compressed_allreduce_dist(
        self, tensor, op, min_ranks, grace_s, timeout_s
    ):
        """EQuARX-style compressed allreduce over ICI/DCN, composed with
        the PR-6 masked partial path: every rank contributes
        ``(quantized grad, w)`` where w comes from the pre-op gate when
        partial mode is on (1.0 otherwise); quantize → all_to_all int8
        → fp32 dequant-accumulate → world/Σw rescale → requantize →
        all_gather int8 — one compiled program whose shape never
        changes whoever straggles."""
        x = self._global(tensor)
        if op is not ReduceOp.SUM:
            raise ValueError(
                f"compressed allreduce supports ReduceOp.SUM only, got {op}"
            )
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            raise TypeError(
                f"compressed allreduce needs a floating dtype, got "
                f"{x.dtype}"
            )
        partial = min_ranks is not None
        if partial:
            grace = (
                float(grace_s) if grace_s is not None
                else _default_partial_grace()
            )
            _check_partial_args(op, x.dtype, min_ranks, self.world)
            w_self = self._gate_weight(grace)
        else:
            w_self = 1.0
        block = _compression_block()
        world = self.world
        length = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        key = ("q8_allreduce", x.shape, str(x.dtype), block)
        prog = self._programs.get(key)
        if prog is None:
            mapped = shard_map(
                _compressed_allreduce_fn(world, length, block),
                mesh=self.mesh,
                in_specs=(P("ranks"), P("ranks")),
                out_specs=(P("ranks"), P("ranks")),
            )
            prog = self._programs[key] = jax.jit(mapped)
        w = self._global(jnp.asarray(w_self, x.dtype))
        out, mask = prog(x, w)
        out = self._local(self._sync(out, "allreduce", timeout_s))
        self._last_wire_bytes = _compressed_wire_bytes(
            world, length, block
        )
        if not partial:
            return out
        maskv = np.asarray(self._local(mask))
        contributed = [r for r in range(world) if maskv[r] > 0]
        skipped = [r for r in range(world) if maskv[r] <= 0]
        if len(contributed) < int(min_ranks):
            raise CollectiveTimeoutError(
                self.name,
                "allreduce",
                grace,
                missing_ranks=skipped,
                detail=f"only {len(contributed)} contributions beat the "
                       f"partial grace window, below min_ranks {min_ranks}",
            )
        if skipped and self.rank == 0:
            record_partial(self.name, "allreduce", skipped)
        return PartialResult(
            value=out, contributed=contributed, skipped=skipped, world=world
        )

    @_recorded("allgather")
    def allgather(self, tensor, timeout_s=None,
                  compression: str | None = None,
                  algo: str | None = None):
        self._check_poisoned("allgather")
        # One compiled lowering (ring over ICI/DCN); algo= accepted for
        # selector parity, the wire estimate below stays honest.
        del algo
        x = self._global(tensor)
        if codec.check_codec(compression) is not None:
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                raise TypeError(
                    f"compressed allgather needs a floating dtype, got "
                    f"{x.dtype}"
                )
            block = _compression_block()
            world = self.world
            shape = x.shape[1:]
            length = int(np.prod(shape)) if shape else 1
            padded = codec.padded_len(length, block)

            def fn(s):
                flat = jnp.pad(
                    s[0].astype(jnp.float32).reshape(-1),
                    (0, padded - length),
                )
                q, scales = codec.quantize_blocked_jax(
                    flat.reshape(-1, block)
                )
                qg = jax.lax.all_gather(q, "ranks", axis=0, tiled=False)
                sg = jax.lax.all_gather(
                    scales, "ranks", axis=0, tiled=False
                )
                deq = (qg.astype(jnp.float32) * sg[..., None]).reshape(
                    world, -1
                )[:, :length]
                return deq.reshape(world, *shape).reshape(
                    world * shape[0] if shape else world, *shape[1:]
                )[None].astype(s.dtype)

            out = self._run(
                ("q8_allgather", x.shape, str(x.dtype), block), fn, x
            )
            q_payload = padded + (padded // block) * 4
            self._last_wire_bytes = int(
                (world - 1) / world * world * q_payload
            )
            return self._local(self._sync(out, "allgather", timeout_s))
        out = self._run(
            ("allgather", x.shape, str(x.dtype)),
            lambda s: jax.lax.all_gather(s[0], "ranks", axis=0, tiled=True)[
                None
            ],
            x,
        )
        nbytes = int(np.prod(x.shape[1:]) * x.dtype.itemsize) if (
            x.ndim > 1
        ) else x.dtype.itemsize
        self._last_wire_bytes = colalgo.wire_bytes_per_rank(
            colalgo.RING, nbytes, self.world, verb="allgather"
        )
        return self._local(self._sync(out, "allgather", timeout_s))

    @_recorded("broadcast")
    def broadcast(self, tensor, root: int = 0, timeout_s=None):
        gathered = self.allgather(
            jnp.asarray(tensor)[None], timeout_s=timeout_s
        )
        return gathered[root]

    @_recorded("reducescatter")
    def reducescatter(self, tensor, op=ReduceOp.SUM, timeout_s=None,
                      compression: str | None = None,
                      algo: str | None = None,
                      min_ranks: int | None = None,
                      grace_s: float | None = None):
        self._check_poisoned("reducescatter")
        if min_ranks is not None:
            if codec.check_codec(compression) is not None:
                raise ValueError(
                    "compressed reducescatter does not compose with "
                    "partial mode yet: drop min_ranks or compression"
                )
            return self._partial_reducescatter_dist(
                tensor, op, min_ranks, grace_s, timeout_s
            )
        x = self._global(tensor)
        if codec.check_codec(compression) is not None:
            if op is not ReduceOp.SUM:
                raise ValueError(
                    "compressed reducescatter supports ReduceOp.SUM only"
                )
            if not jnp.issubdtype(x.dtype, jnp.inexact):
                raise TypeError(
                    f"compressed reducescatter needs a floating dtype, "
                    f"got {x.dtype}"
                )
            if x.shape[1] % self.world:
                raise ValueError(
                    f"reducescatter dim0 {x.shape[1]} not divisible by "
                    f"world {self.world}"
                )
            block = _compression_block()
            world = self.world
            shape = x.shape[1:]
            chunk_shape = (shape[0] // world, *shape[1:])
            clen = int(np.prod(chunk_shape)) if chunk_shape else 1
            padded = codec.padded_len(clen, block)

            def fn(s):
                v = s[0].astype(jnp.float32).reshape(world, clen)
                v = jnp.pad(v, ((0, 0), (0, padded - clen)))
                q, scales = codec.quantize_blocked_jax(
                    v.reshape(world, -1, block)
                )
                q_t = jax.lax.all_to_all(
                    q, "ranks", split_axis=0, concat_axis=0, tiled=True
                )
                s_t = jax.lax.all_to_all(
                    scales, "ranks", split_axis=0, concat_axis=0,
                    tiled=True,
                )
                deq = q_t.astype(jnp.float32) * s_t[..., None]
                red = jnp.sum(deq, axis=0).reshape(-1)[:clen]
                return red.reshape(chunk_shape)[None].astype(s.dtype)

            out = self._run(
                ("q8_reducescatter", x.shape, str(x.dtype), block), fn, x
            )
            q_payload = world * (padded + (padded // block) * 4)
            self._last_wire_bytes = int((world - 1) / world * q_payload)
            return self._local(
                self._sync(out, "reducescatter", timeout_s)
            )
        nbytes = int(np.prod(x.shape[1:]) * x.dtype.itemsize) if (
            x.ndim > 1
        ) else x.dtype.itemsize
        if op is ReduceOp.SUM:
            chosen = colalgo.RING
            if algo is not None:
                chosen = colalgo.choose_algorithm(
                    nbytes, self.world, override=algo,
                    verb="reducescatter",
                )
            if chosen == colalgo.TREE:
                # Small payload: one-shot psum then keep our slice — the
                # latency-optimal branch of the selector.
                full = self.allreduce(tensor, op=op, timeout_s=timeout_s)
                self._last_wire_bytes = colalgo.wire_bytes_per_rank(
                    colalgo.TREE, nbytes, self.world,
                    verb="reducescatter",
                )
                chunk = full.shape[0] // self.world
                return full[self.rank * chunk : (self.rank + 1) * chunk]
            out = self._run(
                ("reducescatter", x.shape, str(x.dtype), op),
                lambda s: jax.lax.psum_scatter(
                    s[0], "ranks", scatter_dimension=0, tiled=True
                )[None],
                x,
            )
            self._last_wire_bytes = colalgo.wire_bytes_per_rank(
                colalgo.RING, nbytes, self.world, verb="reducescatter"
            )
            return self._local(self._sync(out, "reducescatter", timeout_s))
        full = self.allreduce(tensor, op=op, timeout_s=timeout_s)
        chunk = full.shape[0] // self.world
        return full[self.rank * chunk : (self.rank + 1) * chunk]

    # ------------------------------------------------------ async verbs
    def _verb_async(self, verb: str, args, kw) -> CollectiveWork:
        """Dispatch a verb on the group's background dispatch thread
        and return a :class:`FutureCollectiveWork`. Unlike the mesh
        group, the dist verbs block internally (the deadline-bounded
        device sync, partial-gate host reads), so true async needs a
        thread; one thread per group keeps handle-based ops issued in
        program order across ranks. The op records its own
        dispatch→completion interval from inside the thread."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.util import tracing

        if self._dispatch_pool is None:
            self._dispatch_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="xla_col_dispatch"
            )
        wall_start = time.time()
        t0 = time.perf_counter()
        ctx = tracing.active_context()
        payload = args[0] if args else None

        def run():
            prev = self._in_recorded_op
            self._in_recorded_op = True  # record here, not in the verb
            self._last_wire_bytes = None
            with tracing.thread_trace(ctx):
                try:
                    out = getattr(self, verb)(*args, **kw)
                finally:
                    self._in_recorded_op = prev
                record_op(
                    self.name, verb, self.backend_tag, self.world,
                    payload, wall_start, time.perf_counter() - t0,
                    wire_bytes=self._last_wire_bytes,
                )
            return out

        return FutureCollectiveWork(
            self._dispatch_pool.submit(run),
            group_name=self.name,
            verb=verb,
        )

    def allreduce_async(self, tensor, **kw) -> CollectiveWork:
        """Async :meth:`allreduce` (handle-based): composes with
        min_ranks/grace_s, compression and algo exactly like the sync
        verb — the partial gate prices the contribution at dispatch
        time on the dispatch thread."""
        return self._verb_async("allreduce", (tensor,), kw)

    def reducescatter_async(self, tensor, **kw) -> CollectiveWork:
        return self._verb_async("reducescatter", (tensor,), kw)

    def allgather_async(self, tensor, **kw) -> CollectiveWork:
        return self._verb_async("allgather", (tensor,), kw)

    @_recorded("barrier")
    def barrier(self, timeout_s=None):
        self.allreduce(jnp.zeros((), jnp.int32), timeout_s=timeout_s)


async def bootstrap_distributed(
    core,
    group_name: str,
    world_size: int,
    rank: int,
    local_device_ids: Sequence[int] | None = None,
    timeout_s: float | None = None,
):
    """Multi-host jax.distributed bootstrap with head-KV rendezvous.

    Rank 0 publishes a coordinator address in the cluster KV; every rank
    then calls jax.distributed.initialize. This replaces the reference's
    NCCLUniqueID rendezvous actor (nccl_collective_group.py:29-56) with
    the jax coordination service. The coordinator poll is deadline-
    bounded: a rank-0 process that never comes up raises
    CollectiveTimeoutError instead of polling the KV forever.
    """
    import socket
    import time as _time

    t = _default_timeout() if timeout_s is None else float(timeout_s)
    key = f"jaxdist:{group_name}:coordinator"
    if rank == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        host = socket.gethostbyname(socket.gethostname())
        coord = f"{host}:{port}"
        await core.head.call("kv_put", key=key, value=coord.encode())
    else:
        deadline = _time.monotonic() + t
        while True:
            reply = await core.head.call("kv_get", key=key)
            if reply["ok"]:
                coord = reply["value"].decode()
                break
            if _time.monotonic() > deadline:
                raise CollectiveTimeoutError(
                    group_name, "rendezvous", t, missing_ranks=[0],
                    detail="jax.distributed coordinator never published",
                )
            await asyncio.sleep(0.05)

    def _init():
        # CPU cross-process collectives need the gloo implementation
        # (harmless for TPU, where collectives compile to ICI/DCN ops);
        # must be set before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # tpulint: allow(broad-except reason=older jaxlib without the gloo knob; TPU backends ignore it and CPU tests would fail loudly at the first collective)
        except Exception:
            pass
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world_size,
            process_id=rank,
            local_device_ids=local_device_ids,
        )

    await asyncio.get_running_loop().run_in_executor(None, _init)
    return coord
