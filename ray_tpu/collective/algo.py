"""Topology-aware collective algorithm selection.

The Big Send-off (arXiv:2504.18658) shows algorithm choice by message
size and topology is worth integer factors at scale. Three levers here:

- **Ring vs tree by message size** (:func:`choose_algorithm`): a
  flat ring is bandwidth-optimal (moves ``2(n-1)/n * N`` per rank over
  ``2(n-1)`` latency-bound steps); a binomial tree moves the full
  message each of ``~2*log2(n)`` rounds but pays exponentially fewer
  latency terms — it wins below a per-world-size crossover message
  size. The crossover table is overridable via config
  ``COLLECTIVE_ALGO_CROSSOVER``.
- **Hierarchical two-level allreduce for multi-slice DCN meshes**
  (:func:`hierarchical_allreduce`): reduce-scatter inside the slice
  over ICI, allreduce the scattered shards across slice leaders over
  DCN (1/m of the bytes), all-gather back inside the slice. The slow
  inter-domain link carries ``2(s-1)/s * N/m`` instead of
  ``2(n-1)/n * N``.
- **Honest accounting** (:func:`wire_bytes_per_rank`): per-algorithm
  bytes-on-the-wire estimates feeding the flight recorder's wire
  counter and busbw gauge for ops whose transfers happen inside a
  compiled program.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import numpy as np

# Algorithm names accepted by the collective verbs' ``algo=`` kwarg.
HUB = "hub"            # cpu backend's default star reduce (rank 0 hub)
RING = "ring"          # flat ring: bandwidth-optimal, O(n) latency terms
TREE = "tree"          # binomial tree: O(log n) latency terms, full-N rounds
AUTO = "auto"          # pick ring/tree by message size (crossover table)
HIERARCHICAL = "hierarchical"  # two-level ICI/DCN (multi-slice meshes)

ALGOS = (HUB, RING, TREE, AUTO, HIERARCHICAL)

# Default tree→ring crossover (bytes) by world size: the ring's 2(n-1)
# latency terms take longer to amortize as the group grows, so the tree
# keeps winning to larger messages. Largest key <= world applies.
_DEFAULT_CROSSOVER = {
    2: 64 << 10,
    4: 128 << 10,
    8: 256 << 10,
    16: 512 << 10,
    32: 1 << 20,
}


def _crossover_table() -> dict[int, int]:
    """Config-overridable crossover table. ``COLLECTIVE_ALGO_CROSSOVER``
    accepts a single byte count ("65536" — every world size) or
    per-world entries ("2:65536,8:262144")."""
    from ray_tpu._private import config

    spec = str(config.get("COLLECTIVE_ALGO_CROSSOVER") or "").strip()
    if not spec:
        return dict(_DEFAULT_CROSSOVER)
    if ":" not in spec:
        return {2: int(spec)}
    table: dict[int, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        w, _, b = part.partition(":")
        table[int(w)] = int(b)
    return table or dict(_DEFAULT_CROSSOVER)


def crossover_bytes(world: int) -> int:
    """Message size (bytes) at which ring overtakes tree for ``world``."""
    table = _crossover_table()
    eligible = [w for w in table if w <= max(2, int(world))]
    return table[max(eligible)] if eligible else min(table.values())


def choose_algorithm(
    nbytes: int,
    world: int,
    n_slices: int = 1,
    override: str | None = None,
    verb: str = "allreduce",
) -> str:
    """Pick the data-plane algorithm for a payload of ``nbytes``/rank.

    ``override`` short-circuits (any explicit non-AUTO algo wins).
    Multi-slice topologies always take the hierarchical two-level path —
    keeping the DCN hop at 1/m of the bytes beats either flat algorithm
    whenever more than one ICI domain is involved. Otherwise: tree below
    the crossover size, ring above.

    ``verb`` extends the crossover routing to the reduce-scatter /
    all-gather hops of the ZeRO-sharded gradient path: the same
    size-vs-latency tradeoff applies (ring moves (n-1)/n of the bytes
    over n-1 latency-bound hops; the latency-optimal plane — hub star
    on the cpu backend, one-shot lowering on the compiled backends,
    both mapped from TREE — moves more bytes in O(1)/O(log n) rounds),
    minus the hierarchical route, which is an allreduce-only driver
    op."""
    if override is not None and override != AUTO:
        if override not in ALGOS:
            raise ValueError(
                f"unknown collective algo {override!r}; known: {ALGOS}"
            )
        return override
    if n_slices > 1 and verb == "allreduce":
        return HIERARCHICAL
    if world <= 2:
        # Two ranks: ring and tree degenerate to the same exchange; call
        # it tree (one round) so tiny groups never pay ring bookkeeping.
        return TREE
    return TREE if nbytes < crossover_bytes(world) else RING


def wire_bytes_per_rank(
    algo: str,
    nbytes: int,
    world: int,
    n_slices: int = 1,
    compressed_nbytes: int | None = None,
    verb: str = "allreduce",
) -> int:
    """Per-rank bytes ``verb`` moves on the wire under ``algo``.

    ``nbytes`` is the op's LOGICAL per-rank payload by the flight
    recorder's convention: the full flat payload for allreduce and
    reducescatter, this rank's contribution for allgather.
    ``compressed_nbytes`` substitutes the quantized payload size (int8
    data + scales) for the phases that ship compressed data. These are
    the analytic counts the flight recorder's wire counter uses for ops
    whose transfers run inside a compiled program (or through the hub,
    where the payload sizes are measured — this function is the
    estimator for the rest)."""
    n = max(1, int(world))
    payload = int(compressed_nbytes if compressed_nbytes is not None
                  else nbytes)
    if n == 1:
        return 0
    if verb == "reducescatter":
        if algo == RING:
            # n-1 hops, each shipping one 1/n chunk.
            return int((n - 1) / n * payload)
        if algo == HUB:
            # full contribution up, the 1/n chunk back down.
            return payload + payload // n
        if algo == TREE:
            # one-shot / reduce-then-slice: the reduce tree's bytes.
            return int(math.ceil(math.log2(n)) * payload)
        raise ValueError(
            f"unknown reducescatter algo {algo!r}; known: {ALGOS}"
        )
    if verb == "allgather":
        if algo == RING:
            # n-1 hops, each forwarding one rank's contribution.
            return (n - 1) * payload
        if algo == HUB:
            # contribution up, the n gathered chunks back down.
            return (n + 1) * payload
        if algo == TREE:
            # recursive-doubling broadcast of the growing gather.
            return int(math.ceil(math.log2(n)) * n * payload)
        raise ValueError(
            f"unknown allgather algo {algo!r}; known: {ALGOS}"
        )
    if algo == HUB:
        return 2 * payload  # one round trip: contribution up, result down
    if algo == RING:
        # reduce-scatter + all-gather, each (n-1)/n of the payload out.
        return int(2 * (n - 1) / n * payload)
    if algo == TREE:
        # binomial reduce up + broadcast down: log2(n) full-payload sends.
        return int(2 * math.ceil(math.log2(n)) * payload)
    if algo == HIERARCHICAL:
        s = max(1, int(n_slices))
        m = max(1, n // s)
        ici = int(2 * (m - 1) / m * payload) if m > 1 else 0
        dcn = int(2 * (s - 1) / s * (payload / m)) if s > 1 else 0
        return ici + dcn
    raise ValueError(f"unknown collective algo {algo!r}; known: {ALGOS}")


# ------------------------------------------------- hierarchical (jax)
_HIER_PROGRAMS: dict[tuple, Any] = {}

# Per-slice DCN skip bookkeeping for the hierarchical partial op: the
# analogue of the cpu hub's per-rank skip window, at slice granularity.
# A slice chronically skipped on the DCN hop escalates to the head
# (collective_slice_report) which drains the WHOLE slice — feeding the
# same drain-and-replace path the rank-level chronic-skip signal uses.
import threading as _threading

_slice_lock = _threading.Lock()
_slice_skips: dict[str, dict[int, int]] = {}         # group → slice → total
_slice_skip_events: dict[str, list] = {}             # group → [(ts, slice)]
_slice_reported: dict[str, set] = {}                 # group → reported slices


def slice_skip_stats(group: str = "hier") -> dict[int, int]:
    """Per-slice DCN-hop skip counts of the hierarchical partial
    allreduce for ``group`` (merged into
    ``collective.straggler_stats()`` as ``slice_skip_counts``)."""
    with _slice_lock:
        return dict(_slice_skips.get(group, {}))


def _note_slice_skips(group: str, skipped: Sequence[int]) -> None:
    """Count skips, slide the escalation window, and report a slice
    whose skip rate crossed the chronic threshold to the head (which
    drains the whole slice). Fire-and-forget: telemetry and escalation
    must never fail the op."""
    import time as _time

    from ray_tpu._private import config

    window = config.get("COLLECTIVE_SKIP_WINDOW_S")
    threshold = config.get("COLLECTIVE_SKIP_DRAIN_THRESHOLD")
    now = _time.monotonic()
    chronic: list[tuple[int, int]] = []
    with _slice_lock:
        counts = _slice_skips.setdefault(group, {})
        events = _slice_skip_events.setdefault(group, [])
        reported = _slice_reported.setdefault(group, set())
        for si in skipped:
            counts[si] = counts.get(si, 0) + 1
            events.append((now, si))
        cutoff = now - window
        events[:] = [e for e in events if e[0] >= cutoff]
        in_window: dict[int, int] = {}
        for _ts, si in events:
            in_window[si] = in_window.get(si, 0) + 1
        for si, cnt in in_window.items():
            if cnt >= threshold and si not in reported:
                reported.add(si)
                chronic.append((si, cnt))
    if not chronic:
        return
    try:
        import ray_tpu.api as _api

        rt = _api._runtime
        if not rt.ready:
            return
        for si, cnt in chronic:
            rt.run(
                rt.core.head.call(
                    "collective_slice_report",
                    group=group,
                    slice_id=str(si),
                    skips=int(cnt),
                    window_s=float(window),
                )
            )
    # tpulint: allow(broad-except reason=escalation is advisory; without a runtime or a new-enough head the skip metrics still carry the signal)
    except Exception:
        pass


def _slice_count(devices: Sequence) -> int:
    return len({getattr(d, "slice_index", 0) for d in devices})


def hier_dcn_wire_bytes(
    length: int,
    itemsize: int,
    world: int,
    n_slices: int,
    block: int | None = None,
) -> int:
    """Per-rank bytes the hierarchical allreduce's DCN hop moves.

    Uncompressed: the inter-slice allreduce of the 1/m shard,
    ``2(s-1)/s * length/m * itemsize``. With ``block`` (the int8 codec
    on the DCN hop only): int8 data + 1/block fp32 scales through the
    all_to_all + all_gather pair."""
    s = max(1, int(n_slices))
    n = max(1, int(world))
    m = max(1, n // s)
    if s <= 1:
        return 0
    shard_len = max(1, math.ceil(max(1, length) / m))
    if block is None:
        return int(2 * (s - 1) / s * shard_len * itemsize)
    from ray_tpu.collective import codec

    chunk_len = codec.padded_len(-(-shard_len // s), block)
    q_payload = s * (chunk_len + (chunk_len // block) * 4)
    return int(2 * (s - 1) / s * q_payload)


def hierarchical_allreduce(
    tensors: Sequence[Any],
    devices: Sequence | None = None,
    n_slices: int | None = None,
    group: str = "hier",
    min_slices: int | None = None,
    grace_s: float | None = None,
    skip_slices: Sequence[int] | None = None,
    compression: str | None = None,
):
    """Two-level allreduce over a multi-slice device set.

    ``tensors`` is one per-device tensor (single-controller semantics,
    like :class:`XlaMeshGroup`); ``devices`` default to ``jax.devices()``
    and are split into ``n_slices`` contiguous slices (inferred from
    ``device.slice_index`` when present — the fake-slice dryrun shim
    carries it too). The compiled program runs

        psum_scatter over "ici"  →  psum over "dcn"  →  all_gather over "ici"

    so the DCN hop moves ``1/m`` of the payload per rank. Single-slice
    inputs degenerate to a flat psum (same program shape, dcn axis of
    size 1). Returns the per-device reduced tensors, numerically equal
    to a flat allreduce up to fp32 reassociation.

    **DCN-partial mode** (``min_slices=`` / ``skip_slices=``): the
    slice is the failure unit — the intra-slice ICI reduce-scatter and
    all-gather stay EXACT, and the PR-6 masked-partial semantics apply
    only to the inter-slice DCN reduce: a dead or slow slice
    contributes weight 0 and the sum is rescaled by ``S/Σw`` so the
    mean over contributing slices is preserved. Returns a typed
    :class:`PartialResult` whose ``contributed``/``skipped`` lists name
    SLICE indices (``world`` = number of slices). ``skip_slices`` is
    the explicit dead set (drain notices, external health signals);
    the ``RAY_TPU_SLICE_FAIL`` chaos knob adds deterministic failures —
    a "kill"-failed slice is treated as dead, a delayed slice is
    skipped when its delay exceeds ``grace_s`` (config
    COLLECTIVE_PARTIAL_GRACE_S when None). Fewer than ``min_slices``
    surviving slices raises :class:`CollectiveTimeoutError`. Skips
    feed per-slice DCN metrics, ``slice_skip_stats()``, and — past the
    chronic threshold — a ``collective_slice_report`` to the head,
    which drains the whole slice.

    **Compressed DCN hop** (``compression="int8"``): the block-scaled
    int8 codec applies to the inter-slice exchange ONLY — the slow DCN
    link moves int8 + per-block scales (quantize → all_to_all →
    fp32 accumulate → S/Σw rescale → requantize → all_gather) while
    both ICI hops stay exact f32. Composes with partial mode inside
    the same compiled program."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu._private.jax_compat import shard_map
    from ray_tpu.collective import codec
    from ray_tpu.collective.flight_recorder import (
        record_dcn_slices,
        record_op,
        record_partial,
    )
    from ray_tpu.collective.types import (
        CollectiveTimeoutError,
        PartialResult,
    )

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if len(tensors) != n:
        raise ValueError(
            f"expected {n} per-device tensors, got {len(tensors)}"
        )
    s = int(n_slices) if n_slices is not None else _slice_count(devices)
    s = max(1, s)
    if n % s:
        raise ValueError(f"{n} devices do not split into {s} slices")
    m = n // s
    compression = codec.check_codec(compression)
    partial = min_slices is not None or skip_slices is not None

    # Dead/slow slice set: explicit skips, then the chaos knob. A
    # "kill"-failed slice is dead (the in-process analogue of GCE
    # reaping all its hosts); a delayed slice is skipped when its delay
    # exceeds the grace window in partial mode — otherwise the op pays
    # the stall, which is exactly what partial mode exists to avoid.
    skipped = sorted({int(si) for si in (skip_slices or ())})
    from ray_tpu._private import config as _config
    from ray_tpu._private.test_utils import slice_fail_action

    if _config.get("SLICE_FAIL"):
        grace = (
            float(grace_s) if grace_s is not None
            else _config.get("COLLECTIVE_PARTIAL_GRACE_S")
        )
        stall = 0.0
        for si in range(s):
            if si in skipped:
                continue
            action = slice_fail_action(si)
            if action is None:
                continue
            kind, val = action
            if kind == "kill" or (partial and val > grace):
                skipped = sorted(set(skipped) | {si})
                partial = True
            elif kind == "delay":
                stall = max(stall, val)
        if stall > 0:
            time.sleep(stall)
    if partial:
        contributed_slices = [si for si in range(s) if si not in skipped]
        if len(contributed_slices) < max(1, int(min_slices or 1)):
            raise CollectiveTimeoutError(
                group,
                "hier_allreduce",
                grace_s,
                missing_ranks=skipped,
                detail=f"only {len(contributed_slices)} of {s} slices "
                       f"contribute, below min_slices {min_slices}",
            )
    # Runtime devices (unwrap fake-slice shims so device_put accepts them).
    runtime = [getattr(d, "_raytpu_device", d) for d in devices]

    wall_start = time.time()
    t0 = time.perf_counter()
    arrs = [jnp.asarray(t)[None] for t in tensors]
    shape, dtype = arrs[0].shape[1:], arrs[0].dtype
    length = int(np.prod(shape)) if shape else 1
    pad_to = max(1, math.ceil(length / m)) * m
    mesh = Mesh(
        np.asarray(runtime, dtype=object).reshape(s, m), ("dcn", "ici")
    )
    sharding = NamedSharding(mesh, P(("dcn", "ici")))
    x = jax.make_array_from_single_device_arrays(
        (n, *shape), sharding,
        [jax.device_put(a, d) for a, d in zip(arrs, runtime)],
    )

    block = (
        int(_config.get("COLLECTIVE_COMPRESSION_BLOCK"))
        if compression is not None
        else None
    )
    key = (
        s, m, x.shape, str(dtype), tuple(d.id for d in runtime),
        partial, block,
    )
    prog = _HIER_PROGRAMS.get(key)
    if prog is None:
        if partial or compression is not None:
            prog = jax.jit(
                shard_map(
                    _hier_masked_fn(s, m, length, pad_to, block),
                    mesh=mesh,
                    in_specs=(P(("dcn", "ici")), P(("dcn", "ici"))),
                    out_specs=P(("dcn", "ici")),
                )
            )
        else:
            # Classic exact path: untouched program, byte-identical to
            # before partial/compression existed (int dtypes included).
            def fn(v):
                flat = v.reshape(-1)
                flat = jnp.pad(flat, (0, pad_to - length))
                shard = jax.lax.psum_scatter(
                    flat, "ici", scatter_dimension=0, tiled=True
                )
                shard = jax.lax.psum(shard, "dcn")
                full = jax.lax.all_gather(
                    shard, "ici", axis=0, tiled=True
                )
                return full[:length].reshape(v.shape)

            prog = jax.jit(
                shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=P(("dcn", "ici")),
                    out_specs=P(("dcn", "ici")),
                )
            )
        _HIER_PROGRAMS[key] = prog
        if len(_HIER_PROGRAMS) > 64:
            _HIER_PROGRAMS.pop(next(iter(_HIER_PROGRAMS)))
    if partial or compression is not None:
        if not jnp.issubdtype(dtype, jnp.inexact):
            raise TypeError(
                f"partial/compressed hierarchical allreduce needs a "
                f"floating dtype, got {dtype}"
            )
        w = np.ones((n,), dtype=np.dtype(dtype).name)
        for si in skipped:
            w[si * m:(si + 1) * m] = 0
        wx = jax.make_array_from_single_device_arrays(
            (n,), sharding,
            [
                jax.device_put(jnp.asarray(w[i:i + 1]), d)
                for i, d in enumerate(runtime)
            ],
        )
        out = prog(x, wx)
    else:
        out = prog(x)
    # Order results by global row, not shard-iteration order.
    out_shards = sorted(
        out.addressable_shards, key=lambda sh: sh.index[0].start or 0
    )
    result = [shard.data[0] for shard in out_shards]
    dur = time.perf_counter() - t0
    nbytes = int(np.dtype(dtype).itemsize) * length
    itemsize = int(np.dtype(dtype).itemsize)
    ici_bytes = (
        int(2 * (m - 1) / m * nbytes) if m > 1 else 0
    )
    dcn_bytes = hier_dcn_wire_bytes(length, itemsize, n, s, block=block)
    record_op(
        group, "hier_allreduce", "xla_mesh", n, tensors[0],
        wall_start, dur, wire_bytes=ici_bytes + dcn_bytes,
    )
    if s > 1:
        record_dcn_slices(
            group,
            contributed=[si for si in range(s) if si not in skipped],
            skipped=skipped,
            dcn_bytes=dcn_bytes,
            dur=dur,
        )
    if not partial:
        return result
    if skipped:
        record_partial(group, "hier_allreduce", skipped)
        _note_slice_skips(group, skipped)
    return PartialResult(
        value=result,
        contributed=[si for si in range(s) if si not in skipped],
        skipped=skipped,
        world=s,
    )


def _hier_masked_fn(s: int, m: int, length: int, pad_to: int,
                    block: int | None):
    """shard_map body of the masked (and optionally DCN-compressed)
    hierarchical allreduce. ``w`` carries each device's SLICE weight
    (0 = skipped slice): the ICI reduce-scatter stays exact; the DCN
    reduce weights each slice's shard, rescales by ``S/Σw``, and — with
    ``block`` — moves int8 + per-block scales instead of f32 on the
    inter-slice hop (quantize → all_to_all → fp32 accumulate →
    requantize → all_gather), the EQuARX treatment applied to exactly
    the slow link."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.collective import codec

    shard_len = pad_to // m
    if block is not None:
        chunk_len = codec.padded_len(-(-shard_len // s), block)
        total2 = s * chunk_len
        nblk = chunk_len // block

    def fn(v, w):
        flat = v.reshape(-1)
        flat = jnp.pad(flat, (0, pad_to - length))
        shard = jax.lax.psum_scatter(
            flat, "ici", scatter_dimension=0, tiled=True
        )
        wv = w[0]
        cnt = jax.lax.psum(wv, "dcn")
        scale = s / jnp.maximum(cnt, 1.0)
        if block is None:
            red = jax.lax.psum(shard * wv, "dcn") * scale
        else:
            xq = (shard * wv).astype(jnp.float32)
            xq = jnp.pad(xq, (0, total2 - shard_len))
            q, scales = codec.quantize_blocked_jax(
                xq.reshape(s, nblk, block)
            )
            q_t = jax.lax.all_to_all(
                q, "dcn", split_axis=0, concat_axis=0, tiled=True
            )
            s_t = jax.lax.all_to_all(
                scales, "dcn", split_axis=0, concat_axis=0, tiled=True
            )
            deq = q_t.astype(jnp.float32) * s_t[..., None]
            acc = jnp.sum(deq, axis=0) * scale  # fp32 accumulate
            q2, sc2 = codec.quantize_blocked_jax(acc)
            qg = jax.lax.all_gather(q2, "dcn", axis=0, tiled=False)
            sg = jax.lax.all_gather(sc2, "dcn", axis=0, tiled=False)
            red = (
                (qg.astype(jnp.float32) * sg[..., None])
                .reshape(-1)[:shard_len]
                .astype(v.dtype)
            )
        full = jax.lax.all_gather(red, "ici", axis=0, tiled=True)
        return full[:length].reshape(v.shape)

    return fn
