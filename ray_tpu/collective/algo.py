"""Topology-aware collective algorithm selection.

The Big Send-off (arXiv:2504.18658) shows algorithm choice by message
size and topology is worth integer factors at scale. Three levers here:

- **Ring vs tree by message size** (:func:`choose_algorithm`): a
  flat ring is bandwidth-optimal (moves ``2(n-1)/n * N`` per rank over
  ``2(n-1)`` latency-bound steps); a binomial tree moves the full
  message each of ``~2*log2(n)`` rounds but pays exponentially fewer
  latency terms — it wins below a per-world-size crossover message
  size. The crossover table is overridable via config
  ``COLLECTIVE_ALGO_CROSSOVER``.
- **Hierarchical two-level allreduce for multi-slice DCN meshes**
  (:func:`hierarchical_allreduce`): reduce-scatter inside the slice
  over ICI, allreduce the scattered shards across slice leaders over
  DCN (1/m of the bytes), all-gather back inside the slice. The slow
  inter-domain link carries ``2(s-1)/s * N/m`` instead of
  ``2(n-1)/n * N``.
- **Honest accounting** (:func:`wire_bytes_per_rank`): per-algorithm
  bytes-on-the-wire estimates feeding the flight recorder's wire
  counter and busbw gauge for ops whose transfers happen inside a
  compiled program.
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import numpy as np

# Algorithm names accepted by the collective verbs' ``algo=`` kwarg.
HUB = "hub"            # cpu backend's default star reduce (rank 0 hub)
RING = "ring"          # flat ring: bandwidth-optimal, O(n) latency terms
TREE = "tree"          # binomial tree: O(log n) latency terms, full-N rounds
AUTO = "auto"          # pick ring/tree by message size (crossover table)
HIERARCHICAL = "hierarchical"  # two-level ICI/DCN (multi-slice meshes)

ALGOS = (HUB, RING, TREE, AUTO, HIERARCHICAL)

# Default tree→ring crossover (bytes) by world size: the ring's 2(n-1)
# latency terms take longer to amortize as the group grows, so the tree
# keeps winning to larger messages. Largest key <= world applies.
_DEFAULT_CROSSOVER = {
    2: 64 << 10,
    4: 128 << 10,
    8: 256 << 10,
    16: 512 << 10,
    32: 1 << 20,
}


def _crossover_table() -> dict[int, int]:
    """Config-overridable crossover table. ``COLLECTIVE_ALGO_CROSSOVER``
    accepts a single byte count ("65536" — every world size) or
    per-world entries ("2:65536,8:262144")."""
    from ray_tpu._private import config

    spec = str(config.get("COLLECTIVE_ALGO_CROSSOVER") or "").strip()
    if not spec:
        return dict(_DEFAULT_CROSSOVER)
    if ":" not in spec:
        return {2: int(spec)}
    table: dict[int, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        w, _, b = part.partition(":")
        table[int(w)] = int(b)
    return table or dict(_DEFAULT_CROSSOVER)


def crossover_bytes(world: int) -> int:
    """Message size (bytes) at which ring overtakes tree for ``world``."""
    table = _crossover_table()
    eligible = [w for w in table if w <= max(2, int(world))]
    return table[max(eligible)] if eligible else min(table.values())


def choose_algorithm(
    nbytes: int,
    world: int,
    n_slices: int = 1,
    override: str | None = None,
) -> str:
    """Pick the allreduce algorithm for a payload of ``nbytes``/rank.

    ``override`` short-circuits (any explicit non-AUTO algo wins).
    Multi-slice topologies always take the hierarchical two-level path —
    keeping the DCN hop at 1/m of the bytes beats either flat algorithm
    whenever more than one ICI domain is involved. Otherwise: tree below
    the crossover size, ring above."""
    if override is not None and override != AUTO:
        if override not in ALGOS:
            raise ValueError(
                f"unknown collective algo {override!r}; known: {ALGOS}"
            )
        return override
    if n_slices > 1:
        return HIERARCHICAL
    if world <= 2:
        # Two ranks: ring and tree degenerate to the same exchange; call
        # it tree (one round) so tiny groups never pay ring bookkeeping.
        return TREE
    return TREE if nbytes < crossover_bytes(world) else RING


def wire_bytes_per_rank(
    algo: str,
    nbytes: int,
    world: int,
    n_slices: int = 1,
    compressed_nbytes: int | None = None,
) -> int:
    """Per-rank bytes an allreduce moves on the wire under ``algo``.

    ``compressed_nbytes`` substitutes the quantized payload size (int8
    data + scales) for the phases that ship compressed data. These are
    the analytic counts the flight recorder's wire counter uses for ops
    whose transfers run inside a compiled program (or through the hub,
    where the payload sizes are measured — this function is the
    estimator for the rest)."""
    n = max(1, int(world))
    payload = int(compressed_nbytes if compressed_nbytes is not None
                  else nbytes)
    if n == 1:
        return 0
    if algo == HUB:
        return 2 * payload  # one round trip: contribution up, result down
    if algo == RING:
        # reduce-scatter + all-gather, each (n-1)/n of the payload out.
        return int(2 * (n - 1) / n * payload)
    if algo == TREE:
        # binomial reduce up + broadcast down: log2(n) full-payload sends.
        return int(2 * math.ceil(math.log2(n)) * payload)
    if algo == HIERARCHICAL:
        s = max(1, int(n_slices))
        m = max(1, n // s)
        ici = int(2 * (m - 1) / m * payload) if m > 1 else 0
        dcn = int(2 * (s - 1) / s * (payload / m)) if s > 1 else 0
        return ici + dcn
    raise ValueError(f"unknown collective algo {algo!r}; known: {ALGOS}")


# ------------------------------------------------- hierarchical (jax)
_HIER_PROGRAMS: dict[tuple, Any] = {}


def _slice_count(devices: Sequence) -> int:
    return len({getattr(d, "slice_index", 0) for d in devices})


def hierarchical_allreduce(
    tensors: Sequence[Any],
    devices: Sequence | None = None,
    n_slices: int | None = None,
    group: str = "hier",
):
    """Two-level allreduce over a multi-slice device set.

    ``tensors`` is one per-device tensor (single-controller semantics,
    like :class:`XlaMeshGroup`); ``devices`` default to ``jax.devices()``
    and are split into ``n_slices`` contiguous slices (inferred from
    ``device.slice_index`` when present — the fake-slice dryrun shim
    carries it too). The compiled program runs

        psum_scatter over "ici"  →  psum over "dcn"  →  all_gather over "ici"

    so the DCN hop moves ``1/m`` of the payload per rank. Single-slice
    inputs degenerate to a flat psum (same program shape, dcn axis of
    size 1). Returns the per-device reduced tensors, numerically equal
    to a flat allreduce up to fp32 reassociation."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu._private.jax_compat import shard_map
    from ray_tpu.collective.flight_recorder import record_op

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if len(tensors) != n:
        raise ValueError(
            f"expected {n} per-device tensors, got {len(tensors)}"
        )
    s = int(n_slices) if n_slices is not None else _slice_count(devices)
    s = max(1, s)
    if n % s:
        raise ValueError(f"{n} devices do not split into {s} slices")
    m = n // s
    # Runtime devices (unwrap fake-slice shims so device_put accepts them).
    runtime = [getattr(d, "_raytpu_device", d) for d in devices]

    wall_start = time.time()
    t0 = time.perf_counter()
    arrs = [jnp.asarray(t)[None] for t in tensors]
    shape, dtype = arrs[0].shape[1:], arrs[0].dtype
    length = int(np.prod(shape)) if shape else 1
    pad_to = max(1, math.ceil(length / m)) * m
    mesh = Mesh(
        np.asarray(runtime, dtype=object).reshape(s, m), ("dcn", "ici")
    )
    sharding = NamedSharding(mesh, P(("dcn", "ici")))
    x = jax.make_array_from_single_device_arrays(
        (n, *shape), sharding,
        [jax.device_put(a, d) for a, d in zip(arrs, runtime)],
    )

    key = (s, m, x.shape, str(dtype), tuple(d.id for d in runtime))
    prog = _HIER_PROGRAMS.get(key)
    if prog is None:

        def fn(v):
            flat = v.reshape(-1)
            flat = jnp.pad(flat, (0, pad_to - length))
            shard = jax.lax.psum_scatter(
                flat, "ici", scatter_dimension=0, tiled=True
            )
            shard = jax.lax.psum(shard, "dcn")
            full = jax.lax.all_gather(shard, "ici", axis=0, tiled=True)
            return full[:length].reshape(v.shape)

        mapped = shard_map(
            fn,
            mesh=mesh,
            in_specs=P(("dcn", "ici")),
            out_specs=P(("dcn", "ici")),
        )
        prog = _HIER_PROGRAMS[key] = jax.jit(mapped)
        if len(_HIER_PROGRAMS) > 64:
            _HIER_PROGRAMS.pop(next(iter(_HIER_PROGRAMS)))
    out = prog(x)
    # Order results by global row, not shard-iteration order.
    shards = sorted(
        out.addressable_shards, key=lambda sh: sh.index[0].start or 0
    )
    result = [shard.data[0] for shard in shards]
    nbytes = int(np.dtype(dtype).itemsize) * length
    record_op(
        group, "hier_allreduce", "xla_mesh", n, tensors[0],
        wall_start, time.perf_counter() - t0,
        wire_bytes=wire_bytes_per_rank(HIERARCHICAL, nbytes, n, n_slices=s),
    )
    return result
