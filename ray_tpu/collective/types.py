"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class Backend(str, enum.Enum):
    """Which data plane carries the collective.

    XLA_MESH  — devices visible to this process; ops compile to XLA
                collectives over ICI (psum / all_gather / ppermute).
    XLA_DIST  — multi-host jax.distributed; same compiled ops over ICI+DCN.
    CPU       — host-memory tensors over the runtime RPC (the reference's
                gloo role, torch_gloo_collective_group.py).
    AUTO      — XLA_MESH if >1 accelerator device is visible, else CPU.
    """

    XLA_MESH = "xla_mesh"
    XLA_DIST = "xla_dist"
    CPU = "cpu"
    AUTO = "auto"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


UNSET_RANK = -1
