"""Collective types and fault-tolerance exceptions (reference:
python/ray/util/collective/types.py; abort semantics follow the
reference's NCCL-abort / destroy_collective_group contract; partial
K-of-N semantics follow "Efficient AllReduce with Stragglers",
arXiv:2505.23523)."""

from __future__ import annotations

import dataclasses
import enum

from ray_tpu.exceptions import RayTpuError


class Backend(str, enum.Enum):
    """Which data plane carries the collective.

    XLA_MESH  — devices visible to this process; ops compile to XLA
                collectives over ICI (psum / all_gather / ppermute).
    XLA_DIST  — multi-host jax.distributed; same compiled ops over ICI+DCN.
    CPU       — host-memory tensors over the runtime RPC (the reference's
                gloo role, torch_gloo_collective_group.py).
    AUTO      — XLA_MESH if >1 accelerator device is visible, else CPU.
    """

    XLA_MESH = "xla_mesh"
    XLA_DIST = "xla_dist"
    CPU = "cpu"
    AUTO = "auto"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


UNSET_RANK = -1


@dataclasses.dataclass
class PartialResult:
    """Result of a K-of-N partial collective (``allreduce(...,
    min_ranks=K, grace_s=...)``).

    ``value`` is the reduced tensor over the ranks that contributed in
    time; for SUM it is rescaled by ``world / len(contributed)`` so
    ``value / world`` equals the *mean over actual contributors* — a
    skipped rank dilutes nothing, it is simply absent from the mean.
    ``skipped`` names the ranks whose contribution missed the grace
    sub-deadline (empty when everyone arrived); a skipped rank receives
    the SAME value with itself listed in ``skipped``, so the group stays
    op-sequence-synchronized and the straggler rejoins typed instead of
    hanging."""

    value: object
    contributed: list[int]
    skipped: list[int]
    world: int

    @property
    def is_partial(self) -> bool:
        return bool(self.skipped)


class CollectiveWork:
    """Typed handle for an asynchronously dispatched collective op
    (``allreduce_async()`` and friends — the T3-style overlap
    primitive, arXiv:2401.16677).

    The op is in flight the moment the handle exists; ``wait()`` joins
    it and returns exactly what the synchronous verb would have
    (including a :class:`PartialResult` envelope in partial mode), or
    raises the same typed fault-tolerance errors. ``done()`` is a
    non-blocking completion probe. Handles are single-op: ``wait()``
    may be called repeatedly (later calls return the cached result),
    and out-of-order waits across handles are legal — each handle owns
    its own result buffers.

    Flight-recorder contract: the op's recorded wall interval spans
    *dispatch → completion* (not the issuing call window and not the
    ``wait()`` call window), so the comm-exposure attribution credits
    time genuinely hidden behind compute as overlapped."""

    __slots__ = ("group_name", "verb", "_result", "_error", "_finished",
                 "_finalize_cb", "_leak_box", "__weakref__")

    def __init__(self, group_name: str = "", verb: str = ""):
        self.group_name = group_name
        self.verb = verb
        self._result = None
        self._error: BaseException | None = None
        self._finished = False
        # Applied once to the successful result on the waiter's thread
        # (the dispatch layer hangs partial-result bookkeeping here).
        self._finalize_cb = None
        # Sanitizer leak box: set by sanitize.watch_work; wait() marks
        # it closed so a GC'd un-waited handle warns (TPU104's twin).
        self._leak_box = None
        from ray_tpu._private import sanitize

        if sanitize.leaks_enabled():
            sanitize.watch_work(self)

    # Subclasses implement _join(timeout_s) -> result and _probe() ->
    # bool; the caching/raise discipline lives here once.
    def _join(self, timeout_s: float | None):  # pragma: no cover
        raise NotImplementedError

    def _probe(self) -> bool:  # pragma: no cover
        raise NotImplementedError

    def wait(self, timeout_s: float | None = None):
        """Block until the op completes; return its result (or raise
        its typed error). Idempotent — repeat calls replay the cached
        outcome."""
        if not self._finished:
            try:
                out = self._join(timeout_s)
                if self._finalize_cb is not None:
                    out = self._finalize_cb(out)
                self._result = out
            except BaseException as e:
                # A *local* wait deadline is not op completion: the op
                # is still in flight and a later wait() may join it —
                # only terminal outcomes are cached.
                if not getattr(e, "transient_wait", False):
                    self._error = e
                    self._finished = True
                    if self._leak_box is not None:
                        self._leak_box["closed"] = True
                raise
            self._finished = True
            if self._leak_box is not None:
                self._leak_box["closed"] = True
        if self._error is not None:
            raise self._error
        return self._result

    def done(self) -> bool:
        """Non-blocking: has the op completed (successfully or not)?"""
        if self._finished:
            return True
        return self._probe()

    def __repr__(self):
        state = (
            "error" if self._error is not None
            else "done" if self._finished
            else "pending"
        )
        return (
            f"<CollectiveWork {self.verb} group={self.group_name!r} "
            f"{state}>"
        )


class FutureCollectiveWork(CollectiveWork):
    """CollectiveWork over a ``concurrent.futures.Future`` — the shape
    both process-backed backends produce (the cpu hub's op coroutine
    scheduled on the runtime loop; the xla_dist dispatch thread).
    ``finalize`` runs once on the successful result on the waiter's
    thread (partial-result bookkeeping and similar)."""

    __slots__ = ("_future",)

    def __init__(self, future, group_name: str = "", verb: str = "",
                 finalize=None):
        super().__init__(group_name=group_name, verb=verb)
        self._future = future
        self._finalize_cb = finalize

    def _join(self, timeout_s: float | None):
        from concurrent.futures import CancelledError as _FutCancelled
        from concurrent.futures import TimeoutError as _FutTimeout

        try:
            out = self._future.result(timeout_s)
        except _FutCancelled:
            # The group was destroyed under this handle (queued dispatch
            # cancelled): fail typed, like every other in-flight op.
            raise CollectiveGroupDestroyedError(
                self.group_name, self.verb
            ) from None
        except _FutTimeout:
            err = CollectiveTimeoutError(
                self.group_name,
                self.verb,
                timeout_s,
                detail="wait() deadline elapsed before the dispatched "
                       "op completed (the op itself is still bounded "
                       "by its own deadline; this handle can be "
                       "waited again)",
            )
            err.transient_wait = True
            raise err from None
        return out

    def _probe(self) -> bool:
        return self._future.done()


class CollectiveError(RayTpuError):
    """Base for collective fault-tolerance errors. All subclasses keep
    their fields in ``args`` so they survive the task-error pickle path
    (a worker's abort reaches the driver typed, as ``.cause``)."""


class CollectiveTimeoutError(CollectiveError):
    """A collective op or rendezvous missed its deadline.

    ``missing_ranks`` names the ranks whose contribution (or rendezvous
    key) never arrived — None when the caller cannot know (e.g. the hub
    stopped answering)."""

    def __init__(
        self,
        group: str = "",
        op: str = "",
        timeout_s: float | None = None,
        missing_ranks=None,
        detail: str = "",
    ):
        super().__init__(group, op, timeout_s, missing_ranks, detail)
        self.group = group
        self.op = op
        self.timeout_s = timeout_s
        self.missing_ranks = (
            sorted(missing_ranks) if missing_ranks is not None else None
        )
        self.detail = detail

    def __str__(self):
        missing = (
            f" missing ranks {self.missing_ranks}"
            if self.missing_ranks is not None
            else ""
        )
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective {self.op or 'op'} on group {self.group!r} timed "
            f"out after {self.timeout_s}s:{missing or ' no contribution'}"
            f"{tail}"
        )


class CollectiveMemberDiedError(CollectiveError):
    """A group member died (head-declared node/worker death, or the hub
    connection dropped). The group is poisoned: every in-flight and
    future op fails with this until ``reform_group()`` re-forms it from
    the survivors."""

    def __init__(
        self,
        group: str = "",
        op: str = "",
        dead_ranks=(),
        detail: str = "",
    ):
        super().__init__(group, op, tuple(dead_ranks), detail)
        self.group = group
        self.op = op
        self.dead_ranks = sorted(dead_ranks)
        self.detail = detail

    def __str__(self):
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective group {self.group!r} member(s) "
            f"{self.dead_ranks} died"
            + (f" during {self.op}" if self.op else "")
            + f"; reform_group() to continue with the survivors{tail}"
        )


class CollectiveGroupDestroyedError(CollectiveError):
    """The group was destroyed while this op was in flight —
    destroy_collective_group fails pending futures instead of leaving
    their awaiting coroutines pending forever. ``detail`` of
    ``"reformed"`` means the peer incarnation moved to a new epoch (a
    reform happened under this op): reform_in_place()/auto_reform can
    rejoin, a plain destroy cannot."""

    def __init__(self, group: str = "", op: str = "", detail: str = ""):
        super().__init__(group, op, detail)
        self.group = group
        self.op = op
        self.detail = detail

    def __str__(self):
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective group {self.group!r} was destroyed"
            + (f" while {self.op} was in flight" if self.op else "")
            + tail
        )
