"""Collective types and fault-tolerance exceptions (reference:
python/ray/util/collective/types.py; abort semantics follow the
reference's NCCL-abort / destroy_collective_group contract; partial
K-of-N semantics follow "Efficient AllReduce with Stragglers",
arXiv:2505.23523)."""

from __future__ import annotations

import dataclasses
import enum

from ray_tpu.exceptions import RayTpuError


class Backend(str, enum.Enum):
    """Which data plane carries the collective.

    XLA_MESH  — devices visible to this process; ops compile to XLA
                collectives over ICI (psum / all_gather / ppermute).
    XLA_DIST  — multi-host jax.distributed; same compiled ops over ICI+DCN.
    CPU       — host-memory tensors over the runtime RPC (the reference's
                gloo role, torch_gloo_collective_group.py).
    AUTO      — XLA_MESH if >1 accelerator device is visible, else CPU.
    """

    XLA_MESH = "xla_mesh"
    XLA_DIST = "xla_dist"
    CPU = "cpu"
    AUTO = "auto"


class ReduceOp(str, enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


UNSET_RANK = -1


@dataclasses.dataclass
class PartialResult:
    """Result of a K-of-N partial collective (``allreduce(...,
    min_ranks=K, grace_s=...)``).

    ``value`` is the reduced tensor over the ranks that contributed in
    time; for SUM it is rescaled by ``world / len(contributed)`` so
    ``value / world`` equals the *mean over actual contributors* — a
    skipped rank dilutes nothing, it is simply absent from the mean.
    ``skipped`` names the ranks whose contribution missed the grace
    sub-deadline (empty when everyone arrived); a skipped rank receives
    the SAME value with itself listed in ``skipped``, so the group stays
    op-sequence-synchronized and the straggler rejoins typed instead of
    hanging."""

    value: object
    contributed: list[int]
    skipped: list[int]
    world: int

    @property
    def is_partial(self) -> bool:
        return bool(self.skipped)


class CollectiveError(RayTpuError):
    """Base for collective fault-tolerance errors. All subclasses keep
    their fields in ``args`` so they survive the task-error pickle path
    (a worker's abort reaches the driver typed, as ``.cause``)."""


class CollectiveTimeoutError(CollectiveError):
    """A collective op or rendezvous missed its deadline.

    ``missing_ranks`` names the ranks whose contribution (or rendezvous
    key) never arrived — None when the caller cannot know (e.g. the hub
    stopped answering)."""

    def __init__(
        self,
        group: str = "",
        op: str = "",
        timeout_s: float | None = None,
        missing_ranks=None,
        detail: str = "",
    ):
        super().__init__(group, op, timeout_s, missing_ranks, detail)
        self.group = group
        self.op = op
        self.timeout_s = timeout_s
        self.missing_ranks = (
            sorted(missing_ranks) if missing_ranks is not None else None
        )
        self.detail = detail

    def __str__(self):
        missing = (
            f" missing ranks {self.missing_ranks}"
            if self.missing_ranks is not None
            else ""
        )
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective {self.op or 'op'} on group {self.group!r} timed "
            f"out after {self.timeout_s}s:{missing or ' no contribution'}"
            f"{tail}"
        )


class CollectiveMemberDiedError(CollectiveError):
    """A group member died (head-declared node/worker death, or the hub
    connection dropped). The group is poisoned: every in-flight and
    future op fails with this until ``reform_group()`` re-forms it from
    the survivors."""

    def __init__(
        self,
        group: str = "",
        op: str = "",
        dead_ranks=(),
        detail: str = "",
    ):
        super().__init__(group, op, tuple(dead_ranks), detail)
        self.group = group
        self.op = op
        self.dead_ranks = sorted(dead_ranks)
        self.detail = detail

    def __str__(self):
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective group {self.group!r} member(s) "
            f"{self.dead_ranks} died"
            + (f" during {self.op}" if self.op else "")
            + f"; reform_group() to continue with the survivors{tail}"
        )


class CollectiveGroupDestroyedError(CollectiveError):
    """The group was destroyed while this op was in flight —
    destroy_collective_group fails pending futures instead of leaving
    their awaiting coroutines pending forever. ``detail`` of
    ``"reformed"`` means the peer incarnation moved to a new epoch (a
    reform happened under this op): reform_in_place()/auto_reform can
    rejoin, a plain destroy cannot."""

    def __init__(self, group: str = "", op: str = "", detail: str = ""):
        super().__init__(group, op, detail)
        self.group = group
        self.op = op
        self.detail = detail

    def __str__(self):
        tail = f" ({self.detail})" if self.detail else ""
        return (
            f"collective group {self.group!r} was destroyed"
            + (f" while {self.op} was in flight" if self.op else "")
            + tail
        )
