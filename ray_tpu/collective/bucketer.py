"""Gradient bucketer: size-targeted buckets, issued as grads become
ready, synced through async collective handles.

T3 (arXiv:2401.16677) shows that fine-grained tracking-and-triggering
of collectives against remaining compute recovers most of the exposed
communication time in a training step. This module is the host-side
half of that idea (the DDP-bucket lineage): gradients are flattened
into ~``COLLECTIVE_BUCKET_MB`` buckets in **reverse-layer order** — the
order backward produces them — and each full bucket's allreduce is
dispatched immediately via :func:`collective.allreduce_async`, so the
first buckets' sync overlaps the remaining backward compute (and the
join tail overlaps the per-bucket optimizer math). The step loop joins
the handles just before the optimizer update.

Composition: every gradient-sync knob rides the per-bucket op —
``compression="int8"`` (block-scaled codec, optionally with
**error feedback**: the per-bucket quantization residual is added into
the next step's payload before quantizing, so repeated-compression
bias stops accumulating), ``min_ranks=``/``grace_s=`` (K-of-N partial;
skipped ranks surface aggregated on the :class:`PendingSync`), and
per-bucket **algorithm selection** via
:func:`algo.choose_algorithm(nbytes, world, n_slices)` — small buckets
take the latency-optimal tree, large buckets the bandwidth-optimal
ring, closing the "wire the selector into the trainer's gradient sync
by bucket size" follow-up.

Two group shapes are supported: process-backed groups (cpu /
xla_dist — one local gradient tree per process) and the
single-controller mesh group (``expects_per_rank_tensors`` — a list of
per-rank gradient trees, one per device).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from ray_tpu.collective import algo as colalgo
from ray_tpu.collective import codec
from ray_tpu.collective.types import CollectiveWork, PartialResult


def default_bucket_bytes() -> int:
    from ray_tpu._private import config

    return int(float(config.get("COLLECTIVE_BUCKET_MB")) * (1 << 20))


def bucket_analytic_cost_s(
    nbytes: int, world: int, verb: str = "allreduce"
) -> float:
    """Roofline wire time of one bucket's collective on this chip
    generation's ICI (profile.py's bandwidth table + standard ring
    wire factors). The per-bucket analytic floor the in-program
    comm_in_program decomposition compares measured collective time
    against — and what the T3-style overlap scheduler will use to
    decide how much compute a bucket needs to hide behind."""
    from ray_tpu.train import profile

    factor = profile.collective_wire_factor(verb, world)
    if factor <= 0.0:
        return 0.0
    return nbytes * factor / profile.ici_bandwidth_per_chip()


@dataclasses.dataclass
class Bucket:
    """One issued bucket: its leaves (issue order), payload size, and
    the data-plane algorithm the selector picked for it."""

    index: int
    names: list[str]
    nbytes: int
    dtype: str
    algo: str | None
    compression: str | None
    # (name, offset, size, shape) per leaf within the flat payload
    layout: list[tuple[str, int, int, tuple]] = dataclasses.field(
        default_factory=list
    )
    # host scratch bytes this bucket pins while in flight (flat
    # payloads + codec temporaries); released at join, reported to the
    # device-memory ledger as collective_scratch
    scratch_bytes: int = 0


class PendingSync:
    """The in-flight gradient sync: one :class:`CollectiveWork` handle
    per issued bucket. ``wait()`` joins the handles **in issue order**
    (later buckets keep progressing while earlier ones are joined),
    scatters the reduced flat payloads back into leaf shapes, and
    returns ``{name: array}`` (per-rank lists of arrays for the
    single-controller mesh shape). Partial-mode skips are aggregated:
    ``skipped`` is the union of ranks any bucket skipped."""

    def __init__(self, buckets, handles, per_rank: bool, owner=None):
        self._buckets: list[Bucket] = buckets
        self._handles: list[CollectiveWork] = handles
        self._per_rank = per_rank
        self._owner = owner
        self.partials: list[PartialResult] = []

    @property
    def buckets(self) -> list[Bucket]:
        return list(self._buckets)

    @property
    def skipped(self) -> list[int]:
        out: set[int] = set()
        for p in self.partials:
            out |= set(p.skipped)
        return sorted(out)

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout_s: float | None = None) -> dict:
        """Join every bucket handle and return the synced leaves
        ``{name: array}``; for per-rank (mesh) syncs each value is the
        list of per-rank arrays. Typed collective errors propagate
        from the failing bucket's handle."""
        out: dict[str, Any] = {}
        for bucket, handle in zip(self._buckets, self._handles):
            res = handle.wait(timeout_s)
            if self._owner is not None and bucket.scratch_bytes:
                self._owner._scratch_release(bucket.scratch_bytes)
                bucket.scratch_bytes = 0  # idempotent re-waits
            if isinstance(res, PartialResult):
                self.partials.append(res)
                res = res.value
            if self._per_rank:
                flats = [np.asarray(v).reshape(-1) for v in res]
                for name, off, size, shape in bucket.layout:
                    out[name] = [
                        f[off:off + size].reshape(shape) for f in flats
                    ]
            else:
                flat = np.asarray(res).reshape(-1)
                for name, off, size, shape in bucket.layout:
                    out[name] = flat[off:off + size].reshape(shape)
        return out


class BucketStream:
    """Incremental add-as-ready interface: ``add()`` one leaf at a time
    in the order backward produces them (reverse layer order); a bucket
    whose payload crosses the size target is dispatched on the spot —
    its collective overlaps whatever compute follows. ``finish()``
    flushes the stragglers and hands back the :class:`PendingSync`."""

    def __init__(self, bucketer: "GradBucketer"):
        self._b = bucketer
        # dtype → [names, segments (per leaf; per-rank: list of lists),
        # running element count]
        self._open: dict[str, list] = {}
        self._buckets: list[Bucket] = []
        self._handles: list[CollectiveWork] = []
        self._per_rank: bool | None = None

    def add(self, name: str, value) -> None:
        """Queue one gradient leaf. ``value`` is this process's array
        — or, for a single-controller mesh group, the sequence of
        per-rank arrays. Full buckets are issued immediately."""
        per_rank = self._b._per_rank_group
        if self._per_rank is None:
            self._per_rank = per_rank
        if per_rank:
            arrs = [np.asarray(v) for v in value]
            first = arrs[0]
        else:
            first = np.asarray(value)
            arrs = [first]
        key = str(first.dtype)
        entry = self._open.get(key)
        if entry is None:
            entry = self._open[key] = [[], [], 0]
        names, segs, count = entry
        names.append((name, first.shape))
        segs.append([a.reshape(-1) for a in arrs])
        entry[2] = count + int(first.size)
        if entry[2] * first.dtype.itemsize >= self._b.bucket_bytes:
            self._flush(key)

    def _flush(self, dtype_key: str) -> None:
        names, segs, count = self._open.pop(dtype_key)
        if not names:
            return
        per_rank = bool(self._per_rank) and self._b._per_rank_group
        nbytes = count * np.dtype(dtype_key).itemsize
        floating = np.issubdtype(np.dtype(dtype_key), np.floating)
        compression = self._b.compression if floating else None
        index = len(self._buckets)
        bucket = Bucket(
            index=index,
            names=[n for n, _shape in names],
            nbytes=int(nbytes),
            dtype=dtype_key,
            algo=self._b._bucket_algo(nbytes),
            compression=compression,
        )
        off = 0
        for name, shape in names:
            size = int(np.prod(shape)) if shape else 1
            bucket.layout.append((name, off, size, tuple(shape)))
            off += size
        ranks = len(segs[0])
        payloads = []
        for r in range(ranks):
            flat = np.concatenate([s[r] for s in segs]) if len(
                segs
            ) > 1 else segs[0][r]
            payloads.append(np.ascontiguousarray(flat))
        if compression is not None and self._b.error_feedback:
            # Residual keyed by (bucket index, rank): deterministic as
            # long as the model (and therefore the bucket layout) is —
            # a layout change resets the residual inside ErrorFeedback.
            payloads = [
                self._b._ef.apply((index, r), p)
                for r, p in enumerate(payloads)
            ]
        scratch = sum(int(p.nbytes) for p in payloads)
        if compression is not None:
            # int8 wire payload + per-block scales (~0.26x of f32).
            scratch += int(0.26 * scratch)
        bucket.scratch_bytes = scratch
        self._b._scratch_add(scratch)
        value = payloads if per_rank else payloads[0]
        self._handles.append(self._b._issue(value, bucket))
        self._buckets.append(bucket)

    def finish(self) -> PendingSync:
        """Flush every open bucket and return the pending sync."""
        for key in list(self._open):
            self._flush(key)
        pending = PendingSync(
            self._buckets, self._handles,
            per_rank=bool(self._per_rank) and self._b._per_rank_group,
            owner=self._b,
        )
        self._b.last_plan = pending.buckets
        return pending


@dataclasses.dataclass
class ZeroBucket:
    """One bucket of the ZeRO-sharded sync (arXiv:2004.13336): its flat
    payload is laid out as ``world`` equal owner segments, so ONE
    reduce-scatter delivers every owner exactly the reduced gradients
    of the leaves it owns, and ONE all-gather of the updated segments
    rebuilds the full weights — the segment boundaries ARE the
    round-robin ownership partition the checkpoint manifest uses
    (checkpoint/manifest.py ``owned_items``)."""

    index: int
    names: list[str]
    nbytes: int              # logical payload (sum of leaf bytes)
    dtype: str
    algo_rs: str | None      # reduce-scatter hop data plane
    algo_ag: str | None      # all-gather hop data plane
    compression: str | None  # reduce hop only: weights gather exact
    seg_len: int = 0         # padded elements per owner segment
    # (name, owner rank, offset within the owner segment, size, shape)
    layout: list[tuple[str, int, int, int, tuple]] = dataclasses.field(
        default_factory=list
    )
    scratch_bytes: int = 0


class PendingZeroGather:
    """The in-flight weight all-gather of a sharded sync: one handle
    per bucket; ``wait()`` scatters the gathered owner segments back
    into full leaves ``{name: array}`` (identical on every rank by
    construction — the gather is exact)."""

    def __init__(self, buckets, handles, per_rank: bool, owner=None):
        self._buckets: list[ZeroBucket] = buckets
        self._handles: list[CollectiveWork] = handles
        self._per_rank = per_rank
        self._owner = owner

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout_s: float | None = None) -> dict:
        out: dict[str, Any] = {}
        for bucket, handle in zip(self._buckets, self._handles):
            res = handle.wait(timeout_s)
            if self._owner is not None and bucket.scratch_bytes:
                self._owner._scratch_release(bucket.scratch_bytes)
                bucket.scratch_bytes = 0
            if isinstance(res, PartialResult):  # pragma: no cover -
                res = res.value                 # gather hop never partial
            if self._per_rank:
                # Mesh shape: each rank's output is the full tiled
                # concatenation; any one of them carries every segment.
                flat = np.asarray(res[0]).reshape(-1)
            elif isinstance(res, (list, tuple)):
                # cpu allgather: one entry per rank, in rank order.
                flat = np.concatenate(
                    [np.asarray(e).reshape(-1) for e in res]
                )
            else:
                flat = np.asarray(res).reshape(-1)
            for name, owner_rank, off, size, shape in bucket.layout:
                base = owner_rank * bucket.seg_len + off
                out[name] = flat[base:base + size].reshape(shape)
        return out


class PendingZeroSync:
    """The in-flight reduce-scatter hop of a ZeRO-sharded gradient
    sync. ``wait()`` returns the reduced gradients of the leaves THIS
    rank owns (every leaf on the single-controller mesh shape — the
    controller embodies all owners); after the shard-local optimizer
    update, :meth:`allgather_updated` issues the weight all-gather.
    Partial-mode (``min_ranks=``) skips apply to the reduce hop only:
    a straggler's *contribution* can be skipped and rescaled, but every
    owner must deliver its updated segment — a partial gather would
    zero whole weight shards, not merely degrade them."""

    def __init__(self, buckets, handles, per_rank: bool, owner, rank: int):
        self._buckets: list[ZeroBucket] = buckets
        self._handles: list[CollectiveWork] = handles
        self._per_rank = per_rank
        self._owner = owner
        self._rank = int(rank)
        self.partials: list[PartialResult] = []

    @property
    def buckets(self) -> list[ZeroBucket]:
        return list(self._buckets)

    @property
    def skipped(self) -> list[int]:
        out: set[int] = set()
        for p in self.partials:
            out |= set(p.skipped)
        return sorted(out)

    def done(self) -> bool:
        return all(h.done() for h in self._handles)

    def wait(self, timeout_s: float | None = None) -> dict:
        out: dict[str, Any] = {}
        for bucket, handle in zip(self._buckets, self._handles):
            res = handle.wait(timeout_s)
            if self._owner is not None and bucket.scratch_bytes:
                self._owner._scratch_release(bucket.scratch_bytes)
                bucket.scratch_bytes = 0
            if isinstance(res, PartialResult):
                self.partials.append(res)
                res = res.value
            if self._per_rank:
                chunks = [np.asarray(c).reshape(-1) for c in res]
                for name, owner_rank, off, size, shape in bucket.layout:
                    out[name] = chunks[owner_rank][off:off + size].reshape(
                        shape
                    )
            else:
                chunk = np.asarray(res).reshape(-1)
                for name, owner_rank, off, size, shape in bucket.layout:
                    if owner_rank != self._rank:
                        continue
                    out[name] = chunk[off:off + size].reshape(shape)
        return out

    def allgather_updated(
        self, updated: dict, timeout_s: float | None = None
    ) -> PendingZeroGather:
        """Issue the weight all-gather: ``updated`` maps leaf name →
        updated array for the leaves this rank owns (all leaves on the
        mesh shape). Missing owned leaves raise — a silently absent
        update would gather zeros into the weights."""
        b = self._owner
        handles: list[CollectiveWork] = []
        for bucket in self._buckets:
            if self._per_rank:
                segs = np.zeros(
                    (b.world, bucket.seg_len), dtype=bucket.dtype
                )
                for name, owner_rank, off, size, _shape in bucket.layout:
                    segs[owner_rank, off:off + size] = np.asarray(
                        updated[name]
                    ).reshape(-1)
                value: Any = [segs[r] for r in range(b.world)]
                scratch = int(segs.nbytes)
            else:
                seg = np.zeros(bucket.seg_len, dtype=bucket.dtype)
                for name, owner_rank, off, size, _shape in bucket.layout:
                    if owner_rank != self._rank:
                        continue
                    seg[off:off + size] = np.asarray(
                        updated[name]
                    ).reshape(-1)
                value = seg
                scratch = int(seg.nbytes)
            bucket.scratch_bytes = scratch
            b._scratch_add(scratch)
            handles.append(
                b._issue_verb(
                    "allgather", value, algo=bucket.algo_ag,
                    timeout_s=timeout_s,
                )
            )
        return PendingZeroGather(
            self._buckets, handles, per_rank=self._per_rank, owner=b
        )


class GradBucketer:
    """Configured bucketed-sync factory for one collective group.

    ``group_name`` routes through the process-wide group registry;
    ``group`` passes a backend group object directly (driver-side
    :class:`XlaMeshGroup` use). ``algo="auto"`` (default) runs the
    per-bucket :func:`collective.algo.choose_algorithm` selection;
    an explicit algo pins every bucket; ``algo=None`` keeps each
    backend's default data plane. Partial mode always takes the
    default plane — on the cpu backend only the hub owns the grace
    timer."""

    def __init__(
        self,
        group_name: str = "default",
        group=None,
        bucket_bytes: int | None = None,
        compression: str | None = None,
        min_ranks: int | None = None,
        grace_s: float | None = None,
        algo: str | None = colalgo.AUTO,
        error_feedback: bool = False,
        n_slices: int = 1,
        timeout_s: float | None = None,
    ):
        self.group_name = group_name
        self.group = group
        self.bucket_bytes = (
            int(bucket_bytes) if bucket_bytes else default_bucket_bytes()
        )
        self.compression = codec.check_codec(compression)
        self.min_ranks = min_ranks
        self.grace_s = grace_s
        self.algo = algo
        self.n_slices = max(1, int(n_slices))
        self.timeout_s = timeout_s
        if error_feedback and self.compression is None:
            raise ValueError(
                "error_feedback compensates compression error; it "
                "needs compression= set"
            )
        self.error_feedback = bool(error_feedback)
        self._ef = codec.ErrorFeedback() if error_feedback else None
        self.last_plan: list[Bucket] = []
        self.last_zero_plan: list[ZeroBucket] = []
        # In-flight bucket scratch reported to the device-memory ledger
        # (runtime/memory.py): flat payloads + codec temporaries pinned
        # between dispatch and join.
        from ray_tpu.runtime import memory as _rmem

        self._scratch_bytes = 0
        self._mem_reg = _rmem.track(
            f"collective.bucketer.{group_name}",
            kind="collective_scratch",
        )

    def _scratch_add(self, nbytes: int) -> None:
        self._scratch_bytes += int(nbytes)
        self._mem_reg.update(self._scratch_bytes)

    def _scratch_release(self, nbytes: int) -> None:
        self._scratch_bytes = max(0, self._scratch_bytes - int(nbytes))
        self._mem_reg.update(self._scratch_bytes)

    # --------------------------------------------------------- plumbing
    def _group_obj(self):
        if self.group is not None:
            return self.group
        from ray_tpu import collective as col

        return col.get_group(self.group_name)

    @property
    def _per_rank_group(self) -> bool:
        return bool(
            getattr(self._group_obj(), "expects_per_rank_tensors", False)
        )

    @property
    def world(self) -> int:
        return int(self._group_obj().world)

    def _bucket_algo(
        self, nbytes: int, verb: str = "allreduce"
    ) -> str | None:
        if self.algo is None:
            return None
        if self.min_ranks is not None and verb != "allgather":
            # Partial K-of-N needs the backend's default plane (the cpu
            # hub owns the grace timer; ring/tree reject min_ranks).
            # The gather hop never runs partial, so its selection stays.
            return None
        if self.algo != colalgo.AUTO:
            return self.algo
        chosen = colalgo.choose_algorithm(
            int(nbytes), self.world, n_slices=self.n_slices, verb=verb
        )
        # The hierarchical two-level op is a driver-side function, not
        # a group verb — multi-slice meshes fall back to ring here.
        return colalgo.RING if chosen == colalgo.HIERARCHICAL else chosen

    def _issue(self, value, bucket: Bucket) -> CollectiveWork:
        kw: dict = {}
        if bucket.compression is not None:
            kw["compression"] = bucket.compression
        if self.min_ranks is not None:
            kw["min_ranks"] = self.min_ranks
            kw["grace_s"] = self.grace_s
        return self._issue_verb(
            "allreduce", value, algo=bucket.algo, **kw
        )

    def _issue_verb(
        self, verb: str, value, algo=None, timeout_s=None, **kw
    ) -> CollectiveWork:
        kw["timeout_s"] = (
            timeout_s if timeout_s is not None else self.timeout_s
        )
        if algo is not None:
            kw["algo"] = algo
        if self.group is not None:
            return getattr(self.group, f"{verb}_async")(value, **kw)
        from ray_tpu import collective as col

        return getattr(col, f"{verb}_async")(
            value, group_name=self.group_name, **kw
        )

    # ------------------------------------------------------------- API
    def stream(self) -> BucketStream:
        """Incremental interface: feed leaves as backward produces
        them; full buckets dispatch immediately."""
        return BucketStream(self)

    def sync_async(self, grads) -> PendingSync:
        """Bucket and dispatch a whole gradient pytree (leaves issued
        in reverse flatten order — the order backward produced them).
        ``grads`` is this process's tree, or a sequence of per-rank
        trees for a single-controller mesh group. Returns the
        :class:`PendingSync`; reassemble the tree from ``wait()`` with
        :meth:`unflatten`."""
        import jax

        st = self.stream()
        if self._per_rank_group:
            flat_per_rank = [
                jax.tree_util.tree_flatten(t)[0] for t in grads
            ]
            paths, _treedef = self._paths_and_def(grads[0])
            for i in reversed(range(len(paths))):
                st.add(
                    paths[i], [leaves[i] for leaves in flat_per_rank]
                )
        else:
            paths, _treedef = self._paths_and_def(grads)
            leaves = jax.tree_util.tree_flatten(grads)[0]
            for i in reversed(range(len(paths))):
                st.add(paths[i], leaves[i])
        return st.finish()

    def sync(self, grads):
        """Synchronous convenience: bucket, dispatch, join, reassemble
        — the serial baseline the overlap bench compares against (the
        per-bucket knobs still apply; nothing overlaps)."""
        return self.unflatten(grads, self.sync_async(grads).wait())

    # ------------------------------------------------- ZeRO-sharded sync
    def zero_owners(self, names: Sequence[str]) -> dict[str, int]:
        """Round-robin leaf ownership over the SORTED leaf names — the
        exact partition ``checkpoint/manifest.py owned_items`` uses, so
        the optimizer state a rank holds under this sync is the state
        it persists, gather-free."""
        world = max(1, self.world)
        return {n: i % world for i, n in enumerate(sorted(names))}

    def sync_sharded_async(
        self, grads, owners: dict[str, int] | None = None
    ) -> PendingZeroSync:
        """ZeRO-sharded gradient sync (arXiv:2004.13336): instead of
        allreducing full gradients so every replica can apply the full
        update, each bucket's flat payload is laid out as ``world``
        owner segments and REDUCE-SCATTERED — each rank receives only
        the reduced gradients of the leaves it owns, applies the
        shard-local optimizer update (1/world of the optimizer state
        resident), then :meth:`PendingZeroSync.allgather_updated`
        rebuilds the full weights. Wire cost per rank on the ring
        planes is (n-1)/n of the payload per hop — two hops, equal to
        the ring allreduce and strictly below hub/tree.

        Composes with the per-bucket knobs: ``compression="int8"`` (+
        error feedback) rides the reduce hop (the gather ships exact
        weights), ``min_ranks=``/``grace_s=`` applies to the reduce hop
        only, and the crossover selector routes each hop's data plane
        by size. ``owners`` overrides the round-robin partition (tests,
        custom layouts).

        Wire caveat: segments pad to the bucket's HEAVIEST owner, so
        the ≤-allreduce wire property holds exactly when buckets are
        owner-balanced (bucket size a multiple of ``world`` same-size
        leaves — layered models bucket this way naturally); a bucket
        dominated by one owner's leaves pays the padding on both hops
        (bench_zero.py pins the balanced case; the flight recorder's
        measured wire bytes keep the unbalanced case honest)."""
        import jax

        per_rank = self._per_rank_group
        if per_rank:
            trees = list(grads)
            paths, _treedef = self._paths_and_def(trees[0])
            flat_per_rank = [
                jax.tree_util.tree_flatten(t)[0] for t in trees
            ]
            leaf_arrs = [
                [np.asarray(leaves[i]) for leaves in flat_per_rank]
                for i in range(len(paths))
            ]
        else:
            paths, _treedef = self._paths_and_def(grads)
            leaves = jax.tree_util.tree_flatten(grads)[0]
            leaf_arrs = [[np.asarray(v)] for v in leaves]
        owners = owners if owners is not None else self.zero_owners(paths)
        world = max(1, self.world)
        rank = 0 if per_rank else int(getattr(self._group_obj(), "rank", 0))
        buckets: list[ZeroBucket] = []
        handles: list[CollectiveWork] = []
        # dtype → [(name, arrs, size, shape)], running bytes
        open_: dict[str, list] = {}

        def flush(dtype_key: str) -> None:
            entries, _nbytes = open_.pop(dtype_key)
            if not entries:
                return
            floating = np.issubdtype(np.dtype(dtype_key), np.floating)
            compression = self.compression if floating else None
            index = len(buckets)
            seg_fill = [0] * world
            layout = []
            for name, _arrs, size, shape in entries:
                o = owners[name]
                layout.append((name, o, seg_fill[o], size, shape))
                seg_fill[o] += size
            seg_len = max(1, max(seg_fill))
            itemsize = np.dtype(dtype_key).itemsize
            bucket = ZeroBucket(
                index=index,
                names=[name for name, _a, _s, _sh in entries],
                nbytes=sum(s for _n, _a, s, _sh in entries) * itemsize,
                dtype=dtype_key,
                algo_rs=self._bucket_algo(
                    world * seg_len * itemsize, "reducescatter"
                ),
                algo_ag=self._bucket_algo(
                    seg_len * itemsize, "allgather"
                ),
                compression=compression,
                seg_len=seg_len,
                layout=layout,
            )
            ranks = len(entries[0][1])
            payloads = []
            for r in range(ranks):
                flat = np.zeros(world * seg_len, dtype=dtype_key)
                for (name, arrs, size, _shape), (
                    _n, o, off, _size, _sh
                ) in zip(entries, layout):
                    base = o * seg_len + off
                    flat[base:base + size] = arrs[r].reshape(-1)
                payloads.append(flat)
            if compression is not None and self.error_feedback:
                payloads = [
                    self._ef.apply(("zero", index, r), p)
                    for r, p in enumerate(payloads)
                ]
            scratch = sum(int(p.nbytes) for p in payloads)
            if compression is not None:
                scratch += int(0.26 * scratch)
            bucket.scratch_bytes = scratch
            self._scratch_add(scratch)
            kw: dict = {}
            if compression is not None:
                kw["compression"] = compression
            if self.min_ranks is not None:
                kw["min_ranks"] = self.min_ranks
                kw["grace_s"] = self.grace_s
            value = payloads if per_rank else payloads[0]
            handles.append(
                self._issue_verb(
                    "reducescatter", value, algo=bucket.algo_rs, **kw
                )
            )
            buckets.append(bucket)

        # Reverse flatten order — the order backward produces leaves —
        # so the first buckets' reduce-scatter overlaps remaining work.
        for i in reversed(range(len(paths))):
            arrs = leaf_arrs[i]
            first = arrs[0]
            key = str(first.dtype)
            entry = open_.get(key)
            if entry is None:
                entry = open_[key] = [[], 0]
            size = int(first.size) if first.shape else 1
            entry[0].append((paths[i], arrs, size, tuple(first.shape)))
            entry[1] += size * first.dtype.itemsize
            if entry[1] >= self.bucket_bytes:
                flush(key)
        for key in list(open_):
            flush(key)
        pending = PendingZeroSync(
            buckets, handles, per_rank=per_rank, owner=self, rank=rank
        )
        self.last_zero_plan = pending.buckets
        return pending

    def zero_unflatten(self, like, synced: dict):
        """Rebuild ONE full tree from a :class:`PendingZeroGather`
        result (the gathered weights are identical on every rank);
        ``like`` is a single tree, or the per-rank list on the mesh
        shape (its first tree pins the structure)."""
        import jax

        if self._per_rank_group and isinstance(like, (list, tuple)):
            like = like[0]
        paths, treedef = self._paths_and_def(like)
        return jax.tree_util.tree_unflatten(
            treedef, [synced[p] for p in paths]
        )

    def _paths_and_def(self, tree):
        import jax

        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            tree
        )
        paths = [
            jax.tree_util.keystr(path) for path, _leaf in leaves_with_path
        ]
        return paths, treedef

    def unflatten(self, like, synced: dict):
        """Rebuild the gradient tree (or the list of per-rank trees)
        from a :meth:`PendingSync.wait` result."""
        import jax

        if self._per_rank_group:
            paths, treedef = self._paths_and_def(like[0])
            ranks = len(like)
            return [
                jax.tree_util.tree_unflatten(
                    treedef, [synced[p][r] for p in paths]
                )
                for r in range(ranks)
            ]
        paths, treedef = self._paths_and_def(like)
        return jax.tree_util.tree_unflatten(
            treedef, [synced[p] for p in paths]
        )
