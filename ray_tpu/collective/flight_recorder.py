"""Collective flight recorder: always-on, per-op telemetry.

Every collective verb (CPU hub-reduce and the XLA backends) records the
member-visible op latency into a Histogram, the per-rank payload bytes
into a Counter, and the derived achieved *bus* bandwidth into a Gauge —
the attribution layer papers like "Efficient AllReduce with Stragglers"
(arxiv 2505.23523) and T3 (arxiv 2401.16677) assume exists. Each op also
emits a SPAN event onto the task-event pipeline so `ray_tpu timeline`
renders collective ops as slices alongside tasks (and, when the caller
runs under a trace context, parented to the issuing task's span).

Bus bandwidth follows the nccl-tests convention: busbw = algbw × a
verb-specific factor of the world size, where algbw = per-rank bytes /
op time. That makes numbers comparable across verbs and world sizes
(an allreduce moving N bytes/rank does ~2(n-1)/n × N of wire traffic).
"""

from __future__ import annotations

from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Gauge, Histogram

OP_LATENCY = Histogram(
    "ray_tpu_collective_op_latency_seconds",
    "member-visible collective op latency",
    boundaries=(
        0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
        1.0, 5.0, 30.0,
    ),
    tag_keys=("group", "verb", "backend"),
)
OP_BYTES = Counter(
    "ray_tpu_collective_bytes_total",
    "per-rank payload bytes moved by collective ops",
    tag_keys=("group", "verb", "dtype"),
)
BUS_BANDWIDTH = Gauge(
    "ray_tpu_collective_bus_bandwidth_bytes_per_s",
    "achieved bus bandwidth of the most recent collective op "
    "(nccl-tests busbw convention)",
    tag_keys=("group", "verb", "dtype"),
)

# verb → busbw factor as a function of world size (nccl-tests
# performance docs); verbs without an entry (send/recv/permute/
# broadcast/reduce) move each byte once → factor 1.
_BUS_FACTORS = {
    "allreduce": lambda n: 2.0 * (n - 1) / n,
    "allgather": lambda n: (n - 1) / n,
    "reducescatter": lambda n: (n - 1) / n,
}


def payload_info(tensor) -> tuple[int, str]:
    """(per-rank bytes, dtype string) of an op payload. A sequence of
    per-rank tensors (the single-controller mesh backend) reports one
    rank's slice — bandwidth math is per-rank by convention."""
    if tensor is None:
        return 0, "none"
    if isinstance(tensor, (list, tuple)):
        if not tensor:
            return 0, "none"
        tensor = tensor[0]
    nbytes = getattr(tensor, "nbytes", None)
    dtype = getattr(tensor, "dtype", None)
    if nbytes is None:
        try:
            import numpy as np

            arr = np.asarray(tensor)
            nbytes, dtype = arr.nbytes, arr.dtype
        except Exception:  # noqa: BLE001 - unknown payload: size-less
            return 0, "unknown"
    return int(nbytes), str(dtype) if dtype is not None else "unknown"


def record_op(
    group: str,
    verb: str,
    backend: str,
    world: int,
    tensor,
    start: float,
    dur: float,
) -> None:
    """Record one completed collective op (success path only — aborts
    and timeouts are counted by the fault-tolerance counters)."""
    nbytes, dtype = payload_info(tensor)
    OP_LATENCY.observe(
        dur, tags={"group": group, "verb": verb, "backend": backend}
    )
    attrs: dict = {"group": group, "verb": verb, "backend": backend}
    if nbytes:
        tags = {"group": group, "verb": verb, "dtype": dtype}
        OP_BYTES.inc(nbytes, tags=tags)
        attrs["bytes"] = nbytes
        attrs["dtype"] = dtype
        if dur > 0:
            factor = _BUS_FACTORS.get(verb)
            bus = (factor(world) if factor and world else 1.0) * (
                nbytes / dur
            )
            BUS_BANDWIDTH.set(bus, tags=tags)
            attrs["bus_bytes_per_s"] = round(bus, 1)
    tracing.emit_span(f"collective:{verb}", start, dur, **attrs)
