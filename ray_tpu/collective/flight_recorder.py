"""Collective flight recorder: always-on, per-op telemetry.

Every collective verb (CPU hub-reduce and the XLA backends) records the
member-visible op latency into a Histogram, the per-rank payload bytes
into a Counter, and the derived achieved *bus* bandwidth into a Gauge —
the attribution layer papers like "Efficient AllReduce with Stragglers"
(arxiv 2505.23523) and T3 (arxiv 2401.16677) assume exists. Each op also
emits a SPAN event onto the task-event pipeline so `ray_tpu timeline`
renders collective ops as slices alongside tasks (and, when the caller
runs under a trace context, parented to the issuing task's span).

Bus bandwidth follows the nccl-tests convention: busbw = algbw × a
verb-specific factor of the world size, where algbw = per-rank bytes /
op time. That makes numbers comparable across verbs and world sizes
(an allreduce moving N bytes/rank does ~2(n-1)/n × N of wire traffic).
"""

from __future__ import annotations

import threading
import time

from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Gauge, Histogram

OP_LATENCY = Histogram(
    "ray_tpu_collective_op_latency_seconds",
    "member-visible collective op latency",
    boundaries=(
        0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
        1.0, 5.0, 30.0,
    ),
    tag_keys=("group", "verb", "backend"),
)
OP_BYTES = Counter(
    "ray_tpu_collective_bytes_total",
    "per-rank LOGICAL payload bytes moved by collective ops (the caller's "
    "tensor size; see ray_tpu_collective_wire_bytes_total for what "
    "actually crossed the wire)",
    tag_keys=("group", "verb", "dtype"),
)
WIRE_BYTES = Counter(
    "ray_tpu_collective_wire_bytes_total",
    "per-rank bytes this rank actually moved on the wire (compressed "
    "codecs and multi-phase algorithms diverge from the logical size)",
    tag_keys=("group", "verb", "dtype"),
)
COMPRESSION_RATIO = Gauge(
    "ray_tpu_collective_compression_ratio",
    "logical/wire byte ratio of the most recent collective op "
    "(1.0 = uncompressed; ~3.9 for the block-256 int8 codec)",
    tag_keys=("group", "verb"),
)
BUS_BANDWIDTH = Gauge(
    "ray_tpu_collective_bus_bandwidth_bytes_per_s",
    "achieved bus bandwidth of the most recent collective op "
    "(nccl-tests busbw convention)",
    tag_keys=("group", "verb", "dtype"),
)

DCN_CONTRIB = Counter(
    "ray_tpu_collective_dcn_contrib_total",
    "hierarchical-allreduce DCN hop participation by slice and outcome "
    "(contributed vs skipped) — the slice-level health signal the "
    "whole-slice drain escalation reads",
    tag_keys=("group", "slice", "outcome"),
)
DCN_BUS_BANDWIDTH = Gauge(
    "ray_tpu_collective_dcn_bus_bandwidth_bytes_per_s",
    "achieved DCN-hop bus bandwidth of the most recent hierarchical "
    "allreduce, per contributing slice (wire bytes of the inter-slice "
    "exchange / op time)",
    tag_keys=("group", "slice"),
)

PARTIAL_OPS = Counter(
    "ray_tpu_collective_partial_ops_total",
    "collective ops completed in K-of-N partial mode (skipped at least "
    "one straggler's contribution)",
    tag_keys=("group", "verb"),
)
PARTIAL_SKIPS = Counter(
    "ray_tpu_collective_partial_skips_total",
    "times this rank's contribution was skipped by a partial collective",
    tag_keys=("group", "rank"),
)

# verb → busbw factor as a function of world size (nccl-tests
# performance docs); verbs without an entry (send/recv/permute/
# broadcast/reduce) move each byte once → factor 1. `hier_allreduce`
# deliberately has NO entry: its wire traffic depends on the (s, m)
# slice split and on whether the DCN hop is int8-compressed, so a flat
# 2(n-1)/n factor over-reports busbw the moment compression="int8"
# shrinks the DCN bytes. The op always passes explicit wire_bytes=
# computed from its actual split (see algo.hierarchical_allreduce), and
# busbw derives from those measured bytes only.
_BUS_FACTORS = {
    "allreduce": lambda n: 2.0 * (n - 1) / n,
    "allgather": lambda n: (n - 1) / n,
    "reducescatter": lambda n: (n - 1) / n,
}

# --------------------------------------------------- span rate limiting
# Metrics (histogram/counter/gauge) are always recorded — they aggregate.
# SPAN events are per-op list appends that ride the task-event pipeline;
# a >1 kHz storm of sub-ms ops (partial-mode retry storms, tight
# barrier loops) would evict every other event from the head's
# ring buffer. Above _AUTO_RATE_HZ ops/s, sub-_AUTO_DUR_S ops emit
# 1-in-_AUTO_SAMPLE spans (the span carries sample_rate so the timeline
# can re-weight); an explicit sample_rate arg on record_op overrides.
_AUTO_RATE_HZ = 1000
_AUTO_DUR_S = 0.001
_AUTO_SAMPLE = 100

_span_lock = threading.Lock()
# (group, verb) → [window_start_monotonic, ops_in_window, op_counter]
_span_state: dict[tuple, list] = {}


def span_sample(
    group: str, verb: str, dur: float, sample_rate: int | None = None
) -> tuple[bool, int]:
    """Public entry to the high-rate span sampler for other span
    sources with per-event storm potential (serve's per-token decode
    spans key it by (deployment, name)). Same contract as the private
    form below."""
    return _span_sample(group, verb, dur, sample_rate)


def _span_sample(
    group: str, verb: str, dur: float, sample_rate: int | None
) -> tuple[bool, int]:
    """(emit this op's span?, effective 1-in-N rate). N=1 → always."""
    with _span_lock:
        st = _span_state.get((group, verb))
        if st is None:
            if len(_span_state) > 512:  # bound: groups come and go
                _span_state.pop(next(iter(_span_state)))
            st = _span_state[(group, verb)] = [time.monotonic(), 0, 0]
        now = time.monotonic()
        if now - st[0] > 1.0:
            st[0], st[1] = now, 0
        st[1] += 1
        st[2] += 1
        counter, rate_1s = st[2], st[1]
    if sample_rate is not None and sample_rate > 1:
        n = int(sample_rate)
    elif rate_1s > _AUTO_RATE_HZ and dur < _AUTO_DUR_S:
        n = _AUTO_SAMPLE
    else:
        return True, 1
    return counter % n == 0, n


# ------------------------------------------------ op-interval ledger
# Wall-clock (start, end) of every collective op completed in this
# process, ring-bounded. The train step telemetry drains it at step
# close and intersects the intervals with the step's compute phase to
# split collective time into comm_exposed_s vs comm_overlapped_s — the
# baseline the T3-style overlap work must move (today nothing overlaps,
# and the ledger records that honestly rather than assuming it).
_ops_lock = threading.Lock()
_op_intervals: list[tuple[float, float]] = []
_OP_INTERVAL_CAP = 4096


def _note_op_interval(start: float, dur: float) -> None:
    with _ops_lock:
        _op_intervals.append((start, start + dur))
        if len(_op_intervals) > _OP_INTERVAL_CAP:
            del _op_intervals[: _OP_INTERVAL_CAP // 2]


def take_op_intervals() -> list[tuple[float, float]]:
    """Drain the completed-op (start, end) wall-clock intervals recorded
    since the last call (one consumer: the step telemetry)."""
    global _op_intervals
    with _ops_lock:
        out, _op_intervals = _op_intervals, []
    return out


def record_dcn_slices(
    group: str,
    contributed,
    skipped,
    dcn_bytes: int,
    dur: float,
) -> None:
    """Record one hierarchical allreduce's DCN hop at slice
    granularity: a contribution counter per slice (labeled by outcome)
    plus a per-slice DCN busbw gauge for the slices that carried
    traffic. Zero-DCN ops (single slice) record nothing."""
    if not skipped and not contributed:
        return
    for si in contributed:
        DCN_CONTRIB.inc(
            tags={
                "group": group, "slice": str(si), "outcome": "contributed",
            }
        )
        if dcn_bytes > 0 and dur > 0:
            DCN_BUS_BANDWIDTH.set(
                dcn_bytes / dur, tags={"group": group, "slice": str(si)}
            )
    for si in skipped:
        DCN_CONTRIB.inc(
            tags={"group": group, "slice": str(si), "outcome": "skipped"}
        )


def record_partial(group: str, verb: str, skipped) -> None:
    """Record one partial-mode completion: op counter + per-skipped-rank
    counter (the same per-rank series the chronic-straggler signal
    aggregates, plus the dedicated partial counters)."""
    PARTIAL_OPS.inc(tags={"group": group, "verb": verb})
    for r in skipped:
        PARTIAL_SKIPS.inc(tags={"group": group, "rank": str(r)})


def payload_info(tensor) -> tuple[int, str]:
    """(per-rank bytes, dtype string) of an op payload. A sequence of
    per-rank tensors (the single-controller mesh backend) reports one
    rank's slice — bandwidth math is per-rank by convention."""
    if tensor is None:
        return 0, "none"
    if isinstance(tensor, (list, tuple)):
        if not tensor:
            return 0, "none"
        tensor = tensor[0]
    nbytes = getattr(tensor, "nbytes", None)
    dtype = getattr(tensor, "dtype", None)
    if nbytes is None:
        try:
            import numpy as np

            arr = np.asarray(tensor)
            nbytes, dtype = arr.nbytes, arr.dtype
        # tpulint: allow(broad-except reason=an unconvertible payload records as size-less; telemetry must never fail the op it measures)
        except Exception:
            return 0, "unknown"
    return int(nbytes), str(dtype) if dtype is not None else "unknown"


def record_op(
    group: str,
    verb: str,
    backend: str,
    world: int,
    tensor,
    start: float,
    dur: float,
    sample_rate: int | None = None,
    wire_bytes: int | None = None,
) -> None:
    """Record one completed collective op (success path only — aborts
    and timeouts are counted by the fault-tolerance counters).

    ``sample_rate=N`` emits the timeline SPAN for 1-in-N ops (metrics
    are always recorded); with the default None, spans auto-sample at
    1-in-100 once a (group, verb) exceeds 1 kHz of sub-ms ops.

    ``wire_bytes`` is what this rank ACTUALLY moved on the wire when it
    differs from the logical payload size — compressed codecs,
    multi-phase ring/tree algorithms, the hierarchical two-level op.
    When given, the busbw gauge is computed from it directly
    (wire/dur — no verb factor, honest for any algorithm) and the
    logical/wire ratio lands in the compression-ratio gauge."""
    nbytes, dtype = payload_info(tensor)
    _note_op_interval(start, dur)
    OP_LATENCY.observe(
        dur, tags={"group": group, "verb": verb, "backend": backend}
    )
    attrs: dict = {"group": group, "verb": verb, "backend": backend}
    if nbytes:
        tags = {"group": group, "verb": verb, "dtype": dtype}
        OP_BYTES.inc(nbytes, tags=tags)
        attrs["bytes"] = nbytes
        attrs["dtype"] = dtype
        if wire_bytes is not None:
            WIRE_BYTES.inc(wire_bytes, tags=tags)
            attrs["wire_bytes"] = int(wire_bytes)
            if wire_bytes > 0:
                ratio = nbytes / wire_bytes
                COMPRESSION_RATIO.set(
                    ratio, tags={"group": group, "verb": verb}
                )
                attrs["compression_ratio"] = round(ratio, 3)
        if dur > 0:
            if wire_bytes is not None and wire_bytes > 0:
                bus = wire_bytes / dur
            else:
                factor = _BUS_FACTORS.get(verb)
                bus = (factor(world) if factor and world else 1.0) * (
                    nbytes / dur
                )
            BUS_BANDWIDTH.set(bus, tags=tags)
            attrs["bus_bytes_per_s"] = round(bus, 1)
    emit, n = _span_sample(group, verb, dur, sample_rate)
    if not emit:
        return
    if n > 1:
        attrs["sample_rate"] = n
    tracing.emit_span(f"collective:{verb}", start, dur, **attrs)
