"""Collective communication API (reference surface:
python/ray/util/collective/collective.py — init_collective_group :171,
allreduce :328, barrier :368, reduce :381, broadcast :443, allgather :493,
reducescatter :542, send :601, recv :664).

The default data plane is XLA collectives (ICI within a slice, DCN across
slices) instead of NCCL/Gloo; host-memory tensors use the CPU backend over
the runtime RPC. Groups are process-wide, keyed by name, and rendezvous
through the cluster head's KV store.

Fault tolerance: every op takes a deadline (group default via
``init_collective_group(timeout_s=)``, per-op override on each verb);
expiry raises CollectiveTimeoutError naming the missing ranks. Members
register with the head, which fans out node/worker death on the
"collective" pubsub channel — survivors' in-flight and future ops fail
fast with CollectiveMemberDiedError, and ``reform_group()`` re-runs
rendezvous with the survivors (new world size, re-ranked).
"""

from __future__ import annotations

from typing import Any, Sequence

from ray_tpu.collective.types import (
    Backend,
    CollectiveError,
    CollectiveGroupDestroyedError,
    CollectiveMemberDiedError,
    CollectiveTimeoutError,
    ReduceOp,
)

_groups: dict[str, Any] = {}


def _runtime():
    import ray_tpu.api as api

    if not api._runtime.ready:
        raise RuntimeError("ray_tpu.init() must be called before collectives")
    return api._runtime


def _resolve_backend(backend) -> Backend:
    backend = Backend(backend)
    if backend is Backend.AUTO:
        import jax

        accel = [d for d in jax.devices() if d.platform != "cpu"]
        return Backend.XLA_MESH if len(accel) > 1 else Backend.CPU
    return backend


async def _ensure_death_watch(core) -> None:
    """Subscribe this process (once per CoreWorker) to the head's
    "collective" channel and route member-death fan-out to the local
    group objects: a head-declared dead node/worker poisons every group
    it belonged to, immediately."""
    if getattr(core, "_collective_death_watch", False):
        return
    core._collective_death_watch = True

    def _on_event(msg):
        if not isinstance(msg, dict) or msg.get("event") != "member_dead":
            return
        g = _groups.get(msg.get("group"))
        if g is not None and hasattr(g, "_on_member_dead"):
            g._on_member_dead(msg.get("ranks") or [], epoch=msg.get("epoch"))

    await core.subscribe("collective", _on_event)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str | Backend = Backend.AUTO,
    group_name: str = "default",
    timeout_s: float | None = None,
) -> None:
    """Join this process into a named collective group.

    ``timeout_s`` is the group's default deadline for rendezvous and
    every op (config COLLECTIVE_TIMEOUT_S when None); individual verbs
    can override per call."""
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already exists")
    backend = _resolve_backend(backend)
    rt = _runtime()
    if backend is Backend.CPU:
        from ray_tpu.collective.backends.cpu_group import CpuGroup

        async def make():
            g = CpuGroup(
                rt.core, group_name, world_size, rank, timeout_s=timeout_s
            )
            await g.init()
            return g

        _groups[group_name] = rt.run(make())
    elif backend is Backend.XLA_MESH:
        from ray_tpu.collective.backends.xla_group import XlaMeshGroup

        g = XlaMeshGroup(name=group_name)
        if g.world != world_size:
            raise ValueError(
                f"xla_mesh backend: {g.world} local devices != "
                f"world_size {world_size}"
            )
        _groups[group_name] = g
    elif backend is Backend.XLA_DIST:
        from ray_tpu.collective.backends.xla_group import (
            XlaDistGroup,
            bootstrap_distributed,
        )

        rt.run(
            bootstrap_distributed(
                rt.core, group_name, world_size, rank, timeout_s=timeout_s
            )
        )
        _groups[group_name] = XlaDistGroup(
            world_size, rank, timeout_s=timeout_s, name=group_name
        )
    else:
        raise ValueError(f"unsupported backend {backend}")


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    """Destroy the local group object. In-flight op futures on it are
    cancelled/failed (CollectiveGroupDestroyedError) rather than left
    pending."""
    g = _groups.pop(group_name, None)
    if g is not None and hasattr(g, "destroy"):
        _runtime().run(g.destroy())


def reform_group(
    group_name: str = "default", timeout_s: float | None = None
) -> tuple[int, int]:
    """Abort-and-reform: re-run rendezvous with the surviving ranks of a
    poisoned (or op-desynced) group. Every survivor must call this; the
    group keeps its public name but gets a new epoch, dense re-ranking,
    and a fresh op sequence. Returns ``(new_rank, new_world)``."""
    g = get_group(group_name)
    if not hasattr(g, "reform"):
        raise ValueError(
            f"backend {type(g).__name__} does not support reform_group"
        )
    new_g = _runtime().run(g.reform(timeout_s=timeout_s))
    _groups[group_name] = new_g
    return new_g.rank, new_g.world


def straggler_stats(group_name: str = "default") -> dict:
    """Per-rank slowest-contributor telemetry (hub rank only; other
    ranks see zeros). Chronic stragglers show up here — and in the
    collective_straggler_* metrics — before they become timeouts."""
    g = get_group(group_name)
    fn = getattr(g, "straggler_stats", None)
    return fn() if fn is not None else {}


def get_group(group_name: str = "default"):
    g = _groups.get(group_name)
    if g is None:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return g


def get_rank(group_name: str = "default") -> int:
    return getattr(get_group(group_name), "rank", 0)


def get_collective_group_size(group_name: str = "default") -> int:
    return get_group(group_name).world


def _dispatch(name: str, group_name: str, *args, **kw):
    g = get_group(group_name)
    if (
        getattr(g, "expects_per_rank_tensors", False)
        and args
        and args[0] is not None
        and not isinstance(args[0], (list, tuple))
    ):
        raise TypeError(
            f"group {group_name!r} uses the single-controller xla_mesh "
            f"backend: pass a list of {g.world} per-rank tensors, one per "
            "device (each rank is a local device, not a process)"
        )
    fn = getattr(g, name)
    import inspect

    if inspect.iscoroutinefunction(fn):
        from ray_tpu.util import tracing

        coro = fn(*args, **kw)
        # Carry the caller's trace context onto the runtime loop so the
        # flight recorder's op span parents under the issuing task
        # (contextvars do not cross run_coroutine_threadsafe).
        ctx = tracing._active()
        if ctx is not None:
            coro = tracing.carry_context(coro, ctx)
        return _runtime().run(coro)
    return fn(*args, **kw)


def allreduce(
    tensor, group_name: str = "default", op=ReduceOp.SUM, timeout_s=None
):
    return _dispatch(
        "allreduce", group_name, tensor, op=ReduceOp(op), timeout_s=timeout_s
    )


def reduce(
    tensor,
    dst_rank: int = 0,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
):
    return _dispatch(
        "reduce", group_name, tensor, root=dst_rank, op=ReduceOp(op),
        timeout_s=timeout_s,
    )


def broadcast(
    tensor, src_rank: int = 0, group_name: str = "default", timeout_s=None
):
    return _dispatch(
        "broadcast", group_name, tensor, root=src_rank, timeout_s=timeout_s
    )


def allgather(tensor, group_name: str = "default", timeout_s=None):
    return _dispatch("allgather", group_name, tensor, timeout_s=timeout_s)


def reducescatter(
    tensor, group_name: str = "default", op=ReduceOp.SUM, timeout_s=None
):
    return _dispatch(
        "reducescatter", group_name, tensor, op=ReduceOp(op),
        timeout_s=timeout_s,
    )


def barrier(group_name: str = "default", timeout_s=None):
    return _dispatch("barrier", group_name, timeout_s=timeout_s)


def send(
    tensor, dst_rank: int, group_name: str = "default", seq: int = 0,
    timeout_s=None,
):
    return _dispatch(
        "send", group_name, tensor, dst_rank, seq=seq, timeout_s=timeout_s
    )


def recv(
    src_rank: int, group_name: str = "default", seq: int = 0, timeout_s=None
):
    return _dispatch(
        "recv", group_name, src_rank, seq=seq, timeout_s=timeout_s
    )


__all__ = [
    "Backend",
    "ReduceOp",
    "CollectiveError",
    "CollectiveTimeoutError",
    "CollectiveMemberDiedError",
    "CollectiveGroupDestroyedError",
    "init_collective_group",
    "destroy_collective_group",
    "reform_group",
    "straggler_stats",
    "is_group_initialized",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "reduce",
    "broadcast",
    "allgather",
    "reducescatter",
    "barrier",
    "send",
    "recv",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('collective')
del _rlu
