"""Collective communication API (reference surface:
python/ray/util/collective/collective.py — init_collective_group :171,
allreduce :328, barrier :368, reduce :381, broadcast :443, allgather :493,
reducescatter :542, send :601, recv :664).

The default data plane is XLA collectives (ICI within a slice, DCN across
slices) instead of NCCL/Gloo; host-memory tensors use the CPU backend over
the runtime RPC. Groups are process-wide, keyed by name, and rendezvous
through the cluster head's KV store.

Fault tolerance: every op takes a deadline (group default via
``init_collective_group(timeout_s=)``, per-op override on each verb);
expiry raises CollectiveTimeoutError naming the missing ranks. Members
register with the head, which fans out node/worker death on the
"collective" pubsub channel — survivors' in-flight and future ops fail
fast with CollectiveMemberDiedError, and ``reform_group()`` re-runs
rendezvous with the survivors (new world size, re-ranked).

Straggler tolerance: ``allreduce(..., min_ranks=K, grace_s=...)`` is the
partial K-of-N mode (Efficient AllReduce with Stragglers,
arXiv:2505.23523) — the op proceeds with the contributions that beat a
grace sub-deadline (adaptive, p99-derived from the straggler-lag
histogram by default), rescales the mean, and returns PartialResult
naming the skipped ranks; chronic skips escalate to the head's
drain-and-replace path. Partial mode covers allreduce, reducescatter,
and allgather on the cpu backend.

Communication efficiency: ``compression="int8"`` on
allreduce/reducescatter/allgather ships block-scaled int8 + per-block
absmax scales on the wire with fp32 accumulation (EQuARX,
arXiv:2506.17615; collective/codec.py); ``algo=`` picks the data-plane
algorithm — hub/ring/tree on the cpu backend, tree/ring lowering on the
XLA backends, "auto" by message size via the crossover table, and a
hierarchical two-level ICI/DCN allreduce for multi-slice meshes (The
Big Send-off, arXiv:2504.18658; collective/algo.py). The flight
recorder tracks logical vs wire bytes separately
(ray_tpu_collective_wire_bytes_total, compression-ratio gauge).
"""

from __future__ import annotations

from typing import Any, Sequence

from ray_tpu.collective.types import (
    Backend,
    CollectiveError,
    CollectiveGroupDestroyedError,
    CollectiveMemberDiedError,
    CollectiveTimeoutError,
    CollectiveWork,
    FutureCollectiveWork,
    PartialResult,
    ReduceOp,
)

_groups: dict[str, Any] = {}


def _runtime():
    import ray_tpu.api as api

    if not api._runtime.ready:
        raise RuntimeError("ray_tpu.init() must be called before collectives")
    return api._runtime


def _resolve_backend(backend) -> Backend:
    backend = Backend(backend)
    if backend is Backend.AUTO:
        import jax

        accel = [d for d in jax.devices() if d.platform != "cpu"]
        return Backend.XLA_MESH if len(accel) > 1 else Backend.CPU
    return backend


async def _ensure_death_watch(core) -> None:
    """Subscribe this process (once per CoreWorker) to the head's
    "collective" channel and route member-death fan-out to the local
    group objects: a head-declared dead node/worker poisons every group
    it belonged to, immediately."""
    if getattr(core, "_collective_death_watch", False):
        return
    core._collective_death_watch = True

    def _on_event(msg):
        if not isinstance(msg, dict):
            return
        event = msg.get("event")
        if event == "member_dead":
            g = _groups.get(msg.get("group"))
            if g is not None and hasattr(g, "_on_member_dead"):
                g._on_member_dead(
                    msg.get("ranks") or [], epoch=msg.get("epoch")
                )
        elif event == "node_draining":
            # Drain notices ride the same fan-out channel: record them
            # process-locally so the train session (emergency
            # checkpoint) and anyone polling preemption_notice() learns
            # BEFORE the node dies. A drain does NOT poison groups —
            # the node is alive until its deadline.
            from ray_tpu.runtime import drain

            drain.record(msg)
        elif event == "node_undrain":
            from ray_tpu.runtime import drain

            drain.clear(msg.get("node_id"))
        elif event == "profile_capture":
            # Head-triggered compiled-program capture: arm this
            # process's per-step profiler hook (train/profile.py). The
            # same fan-out channel reaches every rank with a live
            # CoreWorker, so one head RPC captures the whole job.
            from ray_tpu.train import profile

            profile.note_capture_request(msg)

    await core.subscribe("collective", _on_event)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str | Backend = Backend.AUTO,
    group_name: str = "default",
    timeout_s: float | None = None,
    auto_reform: bool = False,
) -> None:
    """Join this process into a named collective group.

    ``timeout_s`` is the group's default deadline for rendezvous and
    every op (config COLLECTIVE_TIMEOUT_S when None); individual verbs
    can override per call.

    ``auto_reform``: on an op failure where no member is actually dead
    (a transient timeout, a peer that already reformed), re-run
    rendezvous in place via :func:`reform_in_place` and retry the op
    once — the caller keeps its in-memory state and never sees the
    error. Confirmed member death still raises, so real failures
    escalate to the elastic restart path."""
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already exists")
    backend = _resolve_backend(backend)
    rt = _runtime()
    if backend is Backend.CPU:
        from ray_tpu.collective.backends.cpu_group import CpuGroup

        async def make():
            g = CpuGroup(
                rt.core, group_name, world_size, rank, timeout_s=timeout_s
            )
            await g.init()
            return g

        _groups[group_name] = rt.run(make())
    elif backend is Backend.XLA_MESH:
        from ray_tpu.collective.backends.xla_group import XlaMeshGroup

        g = XlaMeshGroup(name=group_name)
        if g.world != world_size:
            raise ValueError(
                f"xla_mesh backend: {g.world} local devices != "
                f"world_size {world_size}"
            )
        _groups[group_name] = g
    elif backend is Backend.XLA_DIST:
        from ray_tpu.collective.backends.xla_group import (
            XlaDistGroup,
            bootstrap_distributed,
        )

        rt.run(
            bootstrap_distributed(
                rt.core, group_name, world_size, rank, timeout_s=timeout_s
            )
        )
        _groups[group_name] = XlaDistGroup(
            world_size, rank, timeout_s=timeout_s, name=group_name,
            core=rt.core,
        )

        async def _register_dist():
            # Head membership + death watch: the fan-out is what lets
            # XlaDistGroup's deadline-bounded sync abort EARLY (poison
            # polling between bounded waits) instead of at the deadline.
            try:
                await rt.core.head.call(
                    "collective_register",
                    group=group_name,
                    rank=rank,
                    epoch=0,
                    addr=rt.core.addr,
                    node_addr=getattr(rt.core, "node_addr", None),
                    worker_id=getattr(rt.core, "worker_id", None),
                )
            # tpulint: allow(broad-except reason=membership registration on an older head is best-effort; deadlines still work, only death fan-out is lost)
            except Exception:
                pass
            await _ensure_death_watch(rt.core)

        rt.run(_register_dist())
    else:
        raise ValueError(f"unsupported backend {backend}")
    _groups[group_name].auto_reform = auto_reform


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    """Destroy the local group object. In-flight op futures on it are
    cancelled/failed (CollectiveGroupDestroyedError) rather than left
    pending."""
    g = _groups.pop(group_name, None)
    if g is not None and hasattr(g, "destroy"):
        _runtime().run(g.destroy())


def reform_group(
    group_name: str = "default", timeout_s: float | None = None
) -> tuple[int, int]:
    """Abort-and-reform: re-run rendezvous with the surviving ranks of a
    poisoned (or op-desynced) group. Every survivor must call this; the
    group keeps its public name but gets a new epoch, dense re-ranking,
    and a fresh op sequence. Returns ``(new_rank, new_world)``."""
    g = get_group(group_name)
    if not hasattr(g, "reform"):
        raise ValueError(
            f"backend {type(g).__name__} does not support reform_group"
        )
    new_g = _runtime().run(g.reform(timeout_s=timeout_s))
    _groups[group_name] = new_g
    return new_g.rank, new_g.world


def reform_in_place(
    group_name: str = "default", timeout_s: float | None = None
) -> tuple[int, int] | None:
    """Repair a desynced/poisoned group WITHOUT an attempt restart —
    but only when every member is still alive.

    Probes the head (collective_probe cross-references the node and
    worker tables) to confirm whether any silent rank is actually dead.
    If none is — a transient op timeout, or a drain of a node that
    hosts no member — re-runs rendezvous via :func:`reform_group` at
    the SAME shape and returns ``(rank, world)``: callers continue from
    in-memory state, no checkpoint restore, no new attempt. If a member
    is confirmed dead, returns ``None`` — the caller escalates to the
    restart/elastic path."""
    g = get_group(group_name)
    if not hasattr(g, "reform"):
        return None
    rt = _runtime()
    confirmed: set[int] = set()
    try:
        reply = rt.run(
            rt.core.head.call(
                "collective_probe",
                group=getattr(g, "base_name", group_name),
            )
        )
        if reply.get("ok"):
            confirmed = {int(r) for r in reply.get("dead_ranks") or []}
    # tpulint: allow(broad-except reason=the probe is advisory; the local dead set below still gates the reform)
    except Exception:
        pass
    if confirmed and hasattr(g, "_dead"):
        g._dead |= confirmed
    if confirmed or getattr(g, "_dead", None):
        return None
    return reform_group(group_name, timeout_s=timeout_s)


def _reformable(e: Exception) -> bool:
    """Errors worth an in-place reform attempt: transient timeouts, a
    peer that already reformed under us, or a death claim the probe can
    refute (reform_in_place re-checks). A deliberate destroy is not."""
    if isinstance(e, CollectiveTimeoutError):
        return True
    if isinstance(e, CollectiveMemberDiedError):
        return True
    if isinstance(e, CollectiveGroupDestroyedError):
        return "reform" in str(e)
    return False


def straggler_stats(group_name: str = "default") -> dict:
    """Per-rank slowest-contributor telemetry (hub rank only; other
    ranks see zeros). Chronic stragglers show up here — and in the
    collective_straggler_* metrics — before they become timeouts.

    ``slice_skip_counts`` merges the hierarchical allreduce's per-slice
    DCN skip counts (slice index → skips) when ``group_name`` names a
    hierarchical op's group — that op is driver-side and needs no
    init_collective_group, so the group object may not exist."""
    from ray_tpu.collective import algo as _algo

    slice_skips = _algo.slice_skip_stats(group_name)
    g = _groups.get(group_name)
    if g is None:
        if slice_skips:
            return {"slice_skip_counts": slice_skips}
        raise ValueError(
            f"collective group {group_name!r} not initialized"
        )
    fn = getattr(g, "straggler_stats", None)
    out = dict(fn()) if fn is not None else {}
    if slice_skips:
        out["slice_skip_counts"] = slice_skips
    return out


def get_group(group_name: str = "default"):
    g = _groups.get(group_name)
    if g is None:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return g


def get_rank(group_name: str = "default") -> int:
    return getattr(get_group(group_name), "rank", 0)


def get_collective_group_size(group_name: str = "default") -> int:
    return get_group(group_name).world


def _dispatch(name: str, group_name: str, *args, **kw):
    g = get_group(group_name)
    if (
        getattr(g, "expects_per_rank_tensors", False)
        and args
        and args[0] is not None
        and not isinstance(args[0], (list, tuple))
    ):
        raise TypeError(
            f"group {group_name!r} uses the single-controller xla_mesh "
            f"backend: pass a list of {g.world} per-rank tensors, one per "
            "device (each rank is a local device, not a process)"
        )
    # With auto_reform, one failed dispatch may retry once after an
    # in-place reform (no member actually dead → same shape, fresh
    # epoch). The retry re-fetches the group: reform replaced it.
    for attempt in range(2):
        g = get_group(group_name)
        out, err = _dispatch_once(g, name, *args, **kw)
        if err is None:
            return out
        if (
            attempt > 0
            or not getattr(g, "auto_reform", False)
            or not _reformable(err)
        ):
            raise err
        if reform_in_place(group_name) is None:
            raise err  # a member really died: escalate


def _dispatch_once(g, name: str, *args, **kw):
    fn = getattr(g, name)
    import inspect

    try:
        if inspect.iscoroutinefunction(fn):
            from ray_tpu.util import tracing

            coro = fn(*args, **kw)
            # Carry the caller's trace context onto the runtime loop so
            # the flight recorder's op span parents under the issuing
            # task (contextvars do not cross run_coroutine_threadsafe).
            ctx = tracing._active()
            if ctx is not None:
                coro = tracing.carry_context(coro, ctx)
            return _runtime().run(coro), None
        return fn(*args, **kw), None
    except CollectiveError as e:
        return None, e


def _note_partial(out):
    """An active train session charges the skipped fraction of this
    step to the goodput ledger's "degraded" category. sys.modules
    lookup, not an import: no train session can be active unless
    the session module is already loaded, and pure collective
    users must not pay the train-package import."""
    if isinstance(out, PartialResult) and out.skipped:
        import sys

        _session = sys.modules.get("ray_tpu.train.session")
        if _session is not None:
            _session.note_partial_op(out)
    return out


def allreduce(
    tensor,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
):
    """``min_ranks=K`` turns on straggler-tolerant partial mode: the op
    proceeds once K of N contributions have arrived by ``grace_s`` past
    the fastest arrival (adaptive p99-derived window when None, falling
    back to config COLLECTIVE_PARTIAL_GRACE_S), SUM rescaled by
    world/contributors, returning a :class:`PartialResult` that names
    the skipped ranks. Skips feed ``straggler_stats()`` and —
    chronically — the head's drain-and-replace escalation.

    ``compression="int8"`` ships block-scaled int8 on the wire (~3.9x
    fewer bytes; fp32 accumulation — see collective/codec.py);
    ``algo=`` selects the data-plane algorithm ("ring"/"tree"/"auto",
    backend-dependent — see collective/algo.py). With the defaults
    (None everywhere) the classic all-N path runs, byte-identical to
    before."""
    kw: dict = {}
    if min_ranks is not None:
        kw["min_ranks"] = min_ranks
        kw["grace_s"] = grace_s
    if compression is not None:
        kw["compression"] = compression
    if algo is not None:
        kw["algo"] = algo
    return _note_partial(
        _dispatch(
            "allreduce", group_name, tensor, op=ReduceOp(op),
            timeout_s=timeout_s, **kw,
        )
    )


def reduce(
    tensor,
    dst_rank: int = 0,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
):
    return _dispatch(
        "reduce", group_name, tensor, root=dst_rank, op=ReduceOp(op),
        timeout_s=timeout_s,
    )


def broadcast(
    tensor, src_rank: int = 0, group_name: str = "default", timeout_s=None
):
    return _dispatch(
        "broadcast", group_name, tensor, root=src_rank, timeout_s=timeout_s
    )


def allgather(
    tensor,
    group_name: str = "default",
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
):
    """Partial mode (cpu backend): skipped ranks' entries come back
    zero-filled with the skip list in the PartialResult envelope.
    ``compression="int8"`` gathers block-scaled int8 payloads;
    ``algo=`` selects the data plane ("ring"/"auto" — the crossover
    routing the ZeRO weight-allgather hop uses)."""
    kw: dict = {}
    if min_ranks is not None:
        kw["min_ranks"] = min_ranks
        kw["grace_s"] = grace_s
    if compression is not None:
        kw["compression"] = compression
    if algo is not None:
        kw["algo"] = algo
    return _note_partial(
        _dispatch("allgather", group_name, tensor, timeout_s=timeout_s, **kw)
    )


def reducescatter(
    tensor,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
):
    """Partial mode (cpu backend): SUM rescaled by world/contributors
    like allreduce; each rank still receives its own chunk.
    ``compression="int8"`` ships and returns block-scaled int8;
    ``algo=`` selects the data plane ("ring"/"auto" — the crossover
    routing the ZeRO grad reduce-scatter hop uses)."""
    kw: dict = {}
    if min_ranks is not None:
        kw["min_ranks"] = min_ranks
        kw["grace_s"] = grace_s
    if compression is not None:
        kw["compression"] = compression
    if algo is not None:
        kw["algo"] = algo
    return _note_partial(
        _dispatch(
            "reducescatter", group_name, tensor, op=ReduceOp(op),
            timeout_s=timeout_s, **kw,
        )
    )


def _dispatch_async(
    name: str, group_name: str, tensor, **kw
) -> CollectiveWork:
    """Dispatch a verb asynchronously, returning a typed
    :class:`CollectiveWork` handle.

    cpu backend: the op coroutine is scheduled on the runtime loop
    (run_coroutine_threadsafe) — the existing hub/mailbox protocol runs
    unchanged on that background thread while the caller's thread keeps
    computing; the op's flight-recorder interval is its real
    dispatch→completion window on the loop. XLA backends: the group's
    own ``<verb>_async`` (mesh — XLA async dispatch; dist — the
    group's dispatch thread). Async handles do not auto-reform: a
    failure surfaces typed from ``wait()``."""
    g = get_group(group_name)
    if (
        getattr(g, "expects_per_rank_tensors", False)
        and tensor is not None
        and not isinstance(tensor, (list, tuple))
    ):
        raise TypeError(
            f"group {group_name!r} uses the single-controller xla_mesh "
            f"backend: pass a list of {g.world} per-rank tensors, one per "
            "device (each rank is a local device, not a process)"
        )
    fn = getattr(g, name, None)
    import inspect

    if fn is not None and inspect.iscoroutinefunction(fn):
        import asyncio

        from ray_tpu.util import tracing

        rt = _runtime()
        coro = fn(tensor, **kw)
        ctx = tracing._active()
        if ctx is not None:
            coro = tracing.carry_context(coro, ctx)
        return FutureCollectiveWork(
            asyncio.run_coroutine_threadsafe(coro, rt.loop),
            group_name=group_name,
            verb=name,
            finalize=_note_partial,
        )
    async_fn = getattr(g, f"{name}_async", None)
    if async_fn is None:
        raise ValueError(
            f"backend {type(g).__name__} does not support async {name}"
        )
    work = async_fn(tensor, **kw)
    work._finalize_cb = _note_partial
    return work


def _async_kwargs(
    op, timeout_s, min_ranks, grace_s, compression, algo, with_op=True
) -> dict:
    kw: dict = {"timeout_s": timeout_s}
    if with_op:
        kw["op"] = ReduceOp(op)
    if min_ranks is not None:
        kw["min_ranks"] = min_ranks
        kw["grace_s"] = grace_s
    if compression is not None:
        kw["compression"] = compression
    if algo is not None:
        kw["algo"] = algo
    return kw


def allreduce_async(
    tensor,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
) -> CollectiveWork:
    """Asynchronous :func:`allreduce`: the op is in flight when this
    returns; ``.wait()`` joins it (same result, same typed errors, same
    PartialResult envelope in partial mode) and ``.done()`` probes
    completion. The overlap primitive the gradient bucketer builds on —
    issue bucket syncs during remaining backward compute, join before
    the optimizer update. Composes with ``min_ranks=``/``grace_s=``,
    ``compression=`` and ``algo=`` exactly like the sync verb."""
    return _dispatch_async(
        "allreduce", group_name, tensor,
        **_async_kwargs(op, timeout_s, min_ranks, grace_s, compression,
                        algo),
    )


def reducescatter_async(
    tensor,
    group_name: str = "default",
    op=ReduceOp.SUM,
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
) -> CollectiveWork:
    """Asynchronous :func:`reducescatter` — see :func:`allreduce_async`."""
    return _dispatch_async(
        "reducescatter", group_name, tensor,
        **_async_kwargs(op, timeout_s, min_ranks, grace_s, compression,
                        algo),
    )


def allgather_async(
    tensor,
    group_name: str = "default",
    timeout_s=None,
    min_ranks: int | None = None,
    grace_s: float | None = None,
    compression: str | None = None,
    algo: str | None = None,
) -> CollectiveWork:
    """Asynchronous :func:`allgather` — see :func:`allreduce_async`."""
    return _dispatch_async(
        "allgather", group_name, tensor,
        **_async_kwargs(None, timeout_s, min_ranks, grace_s, compression,
                        algo, with_op=False),
    )


def barrier(group_name: str = "default", timeout_s=None):
    return _dispatch("barrier", group_name, timeout_s=timeout_s)


def send(
    tensor, dst_rank: int, group_name: str = "default", seq: int = 0,
    timeout_s=None,
):
    return _dispatch(
        "send", group_name, tensor, dst_rank, seq=seq, timeout_s=timeout_s
    )


def recv(
    src_rank: int, group_name: str = "default", seq: int = 0, timeout_s=None
):
    return _dispatch(
        "recv", group_name, src_rank, seq=seq, timeout_s=timeout_s
    )


__all__ = [
    "Backend",
    "ReduceOp",
    "PartialResult",
    "CollectiveError",
    "CollectiveTimeoutError",
    "CollectiveMemberDiedError",
    "CollectiveGroupDestroyedError",
    "init_collective_group",
    "destroy_collective_group",
    "reform_group",
    "reform_in_place",
    "straggler_stats",
    "is_group_initialized",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "reduce",
    "broadcast",
    "allgather",
    "reducescatter",
    "barrier",
    "send",
    "recv",
    "CollectiveWork",
    "allreduce_async",
    "reducescatter_async",
    "allgather_async",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('collective')
del _rlu
