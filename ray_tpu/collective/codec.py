"""Block-scaled int8 codec for compressed collectives.

EQuARX (arXiv:2506.17615) shows a block-scaled quantized allreduce inside
XLA recovers a near-2x communication speedup with negligible quality
loss. This module is the codec both backends share:

- **Quantization** is per-block absmax: the flat payload is padded to a
  multiple of ``block`` elements, each block gets one fp32 scale
  ``absmax / 127``, and values quantize to ``round(x / scale)`` clipped
  to [-127, 127]. Bytes on the wire drop to
  ``1 + 4/block`` per element vs 4 for f32 (~3.9x at block=256).
- **Accumulation stays fp32**: reduction always dequantizes first, sums
  in float32, then requantizes — int8 is a *wire* format, never an
  accumulator (an int8 sum of K ranks would overflow at K=2).
- **Error bound**: per element, ``|x - dq(q(x))| <= scale/2`` where
  ``scale`` is that element's block scale — i.e. absmax(block)/254.
  A reduce over K contributors with one requantize of the result is
  bounded by ``sum_k scale_k/2 + scale_result/2``.

- **Error feedback** (:func:`quantization_residual` /
  :class:`ErrorFeedback`): a sender can carry the per-payload
  quantization error into the next step's payload before quantizing,
  so repeated compression bias stops accumulating across steps — the
  gradient bucketer turns this on with ``error_feedback=True``.

The numpy half serializes to a plain dict (``to_wire``/``from_wire``) so
it rides the existing collective RPC serializer; the jax half
(:func:`quantize_jax` / :func:`dequantize_jax`) is shape-static and
jit-safe so the XLA backends can compile it *around* their collectives
(quantize → all_to_all/all_gather int8 → dequant) — the compiled shape
never depends on the data.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

# Codec names accepted by the collective verbs' ``compression=`` kwarg.
INT8 = "int8"
CODECS = (INT8,)

# Per-block element count for the absmax scales. 256 puts the scale
# overhead at 4/256 = 1.6% of the int8 payload.
DEFAULT_BLOCK = 256

_QMAX = 127.0


def check_codec(compression: str | None) -> str | None:
    """Validate a ``compression=`` kwarg (None passes through)."""
    if compression is None:
        return None
    if compression not in CODECS:
        raise ValueError(
            f"unknown compression {compression!r}; supported: {CODECS}"
        )
    return compression


@dataclasses.dataclass
class Quantized:
    """One block-scaled int8 payload.

    ``q`` is the padded flat int8 tensor ``(nblocks * block,)``;
    ``scales`` the per-block fp32 scales ``(nblocks,)``; ``shape`` /
    ``dtype`` restore the original array on dequantize."""

    q: np.ndarray
    scales: np.ndarray
    shape: tuple
    dtype: str
    block: int = DEFAULT_BLOCK

    @property
    def wire_nbytes(self) -> int:
        """Bytes this payload puts on the wire (int8 data + scales)."""
        return int(self.q.nbytes + self.scales.nbytes)

    @property
    def logical_nbytes(self) -> int:
        """Bytes the uncompressed payload would have moved."""
        return int(
            np.dtype(self.dtype).itemsize * math.prod(self.shape or (1,))
        )

    def max_error(self) -> float:
        """Worst-case per-element round-trip error (absmax/254)."""
        return float(self.scales.max(initial=0.0)) / 2.0


def _blocks(flat: np.ndarray, block: int) -> np.ndarray:
    n = flat.size
    nblk = max(1, math.ceil(n / block))
    padded = np.zeros(nblk * block, np.float32)
    padded[:n] = flat
    return padded.reshape(nblk, block)


def quantize(
    arr: Any, block: int = DEFAULT_BLOCK, out_dtype: str | None = None
) -> Quantized:
    """Block-scaled int8 quantization of any array-like (fp32 math)."""
    a = np.asarray(arr)
    shape, dtype = a.shape, str(out_dtype or a.dtype)
    blocks = _blocks(a.astype(np.float32).reshape(-1), block)
    scales = (np.max(np.abs(blocks), axis=1) / _QMAX).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0).astype(np.float32)
    q = np.clip(np.rint(blocks / safe[:, None]), -_QMAX, _QMAX).astype(
        np.int8
    )
    return Quantized(
        q=q.reshape(-1), scales=scales, shape=shape, dtype=dtype, block=block
    )


def dequantize(qt: Quantized, dtype: str | None = None) -> np.ndarray:
    """Inverse of :func:`quantize`; accumulate-grade fp32 by default
    (pass ``dtype`` to cast back to the original payload dtype)."""
    blocks = qt.q.reshape(-1, qt.block).astype(np.float32)
    flat = (blocks * qt.scales[:, None]).reshape(-1)
    n = math.prod(qt.shape or (1,))
    out = flat[:n].reshape(qt.shape)
    return out.astype(dtype) if dtype is not None else out


_WIRE_KEY = "__q8__"


def to_wire(qt: Quantized) -> dict:
    """Serializer-friendly dict (numpy leaves ride the buffer path)."""
    return {
        _WIRE_KEY: 1,
        "q": qt.q,
        "scales": qt.scales,
        "shape": list(qt.shape),
        "dtype": qt.dtype,
        "block": qt.block,
    }


def is_wire(payload: Any) -> bool:
    return isinstance(payload, dict) and _WIRE_KEY in payload


def from_wire(d: dict) -> Quantized:
    return Quantized(
        q=np.asarray(d["q"], np.int8),
        scales=np.asarray(d["scales"], np.float32),
        shape=tuple(d["shape"]),
        dtype=str(d["dtype"]),
        block=int(d["block"]),
    )


def quantization_residual(
    arr: Any, block: int | None = None
) -> np.ndarray:
    """The local error one wire round trip of this codec would commit:
    ``x - dequantize(quantize(x))``, fp32.

    This is the error-feedback primitive (1-bit SGD / EF-SGD lineage):
    a sender that adds this residual into the NEXT step's payload
    before quantizing stops repeated-compression bias from
    accumulating — each step transmits what the previous step's
    quantizer dropped. The quantizer here mirrors the wire path
    exactly for the cpu hub (same codec, same block size); the XLA
    backends' in-program quantizer differs only in chunk-boundary
    padding, so the residual remains a faithful first-order
    correction there too."""
    if block is None:
        from ray_tpu._private import config

        block = int(config.get("COLLECTIVE_COMPRESSION_BLOCK"))
    x = np.asarray(arr, np.float32)
    return x - dequantize(quantize(x, block=block))


class ErrorFeedback:
    """Per-key residual accumulator for repeated compressed syncs.

    One instance per sender; ``apply(key, x)`` returns the
    residual-compensated payload to hand to the compressed collective
    and updates the stored residual to the error the codec will commit
    on it. A key whose payload changes shape (re-bucketing, elastic
    resize) resets silently — stale residuals must not leak across
    layouts."""

    def __init__(self, block: int | None = None):
        self.block = block
        self._residuals: dict = {}

    def apply(self, key, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        r = self._residuals.get(key)
        if r is not None and r.shape == x.shape:
            x = x + r
        self._residuals[key] = quantization_residual(x, self.block)
        return x

    def reset(self) -> None:
        self._residuals.clear()


# ------------------------------------------------------------------ jax
# Shape-static codec for use INSIDE compiled programs (shard_map bodies).
# Everything below is jit-safe: padded length and block count are
# functions of the static input shape only, never the data.

def padded_len(n: int, block: int = DEFAULT_BLOCK) -> int:
    return max(1, math.ceil(n / block)) * block


def quantize_jax(x, block: int = DEFAULT_BLOCK):
    """``x`` (any shape) → ``(q int8 (nblk, block), scales f32 (nblk,))``."""
    import jax.numpy as jnp

    flat = x.astype(jnp.float32).reshape(-1)
    total = padded_len(flat.shape[0], block)
    flat = jnp.pad(flat, (0, total - flat.shape[0]))
    blocks = flat.reshape(-1, block)
    scales = jnp.max(jnp.abs(blocks), axis=1) / _QMAX
    safe = jnp.where(scales > 0, scales, 1.0)
    q = jnp.clip(jnp.round(blocks / safe[:, None]), -_QMAX, _QMAX).astype(
        jnp.int8
    )
    return q, scales.astype(jnp.float32)


def quantize_blocked_jax(blocks):
    """``blocks (..., nblk, block)`` (already block-aligned, f32) →
    ``(q int8 same shape, scales f32 (..., nblk))`` — the in-program
    form the XLA backends use so the chunk axis survives for
    all_to_all."""
    import jax.numpy as jnp

    scales = jnp.max(jnp.abs(blocks), axis=-1) / _QMAX
    safe = jnp.where(scales > 0, scales, 1.0)
    q = jnp.clip(
        jnp.round(blocks / safe[..., None]), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return q, scales.astype(jnp.float32)


def dequantize_jax(q, scales):
    """``(q (..., nblk, block), scales (..., nblk))`` → flat f32 of the
    padded length (caller slices back to the logical size)."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32) * scales[..., None]).reshape(
        *q.shape[:-2], -1
    )


def wire_nbytes_jax(n_elements: int, block: int = DEFAULT_BLOCK) -> int:
    """Wire bytes of one quantized payload of ``n_elements`` (int8 data
    + fp32 scales) — the analytic size the XLA backends report, since a
    compiled program's internal transfers cannot be measured from the
    host."""
    total = padded_len(n_elements, block)
    return total + (total // block) * 4
