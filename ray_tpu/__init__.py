"""ray_tpu: a TPU-native distributed computing framework.

Capability-equivalent to Ray (tasks / actors / objects / placement groups /
collectives / Train / Tune / Data / Serve / RL) but designed for TPU from
the ground up: the accelerator data plane is XLA collectives over ICI/DCN
compiled into programs (jax / pjit / shard_map / Pallas), and the CPU-side
runtime orchestrates processes the way the reference's C++ core does
(SURVEY.md maps every subsystem to its reference counterpart).
"""

from ray_tpu.version import __version__

__all__ = ["__version__"]


def __getattr__(name):
    # Lazy-load the core API so `import ray_tpu.models` does not drag in the
    # runtime (and vice versa).
    _core_api = {
        "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
        "broadcast", "kill", "cancel", "get_actor", "method", "ObjectRef",
        "ObjectRefGenerator", "available_resources", "cluster_resources",
        "nodes",
    }
    if name in _core_api:
        try:
            import ray_tpu.api as _api
        except ImportError as e:
            raise AttributeError(
                f"ray_tpu.{name} requires the core runtime (ray_tpu.api), "
                f"which failed to import: {e}"
            ) from e
        return getattr(_api, name)
    if name == "cross_language":
        import ray_tpu.cross_language as _xl

        return _xl
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
