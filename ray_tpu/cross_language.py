"""Cross-language task API: call functions DEFINED in foreign workers.

Reference: python/ray/cross_language.py — ``ray.cross_language.
cpp_function("Plus").remote(1, 2)`` submits a task executed by a C++
worker whose binary registered ``Plus`` with RAY_REMOTE. The TPU-native
equivalent: C++ functions register via RAYTPU_REMOTE
(cpp/include/raytpu/ray_remote.h), the node manager spawns the
configured worker binary (config CPP_WORKER_CMD) for leases whose
runtime_env is ``{"language": "cpp"}``, and the task rides the NORMAL
submission path — ownership, leasing, retries — with fn_id
``cfn:<name>`` and msgpack-only arguments/results (pickle never
crosses the language boundary).

The other direction (C++ driver calling Python functions registered
with ``ray_tpu._private.xlang.register_function``) lives in
cpp/src/client.cpp (Driver::Call).

Usage::

    import ray_tpu
    ray_tpu.init(_system_config={
        "CPP_WORKER_CMD": "cpp/build/raytpu_worker",
    })
    add = ray_tpu.cross_language.cpp_function("Add")
    assert ray_tpu.get(add.remote(19, 23)) == 42
"""

from __future__ import annotations


class CppFunction:
    """Handle to a C++-registered remote function (by name)."""

    def __init__(
        self,
        name: str,
        *,
        resources: dict | None = None,
        max_retries: int = 3,
    ):
        if ":" in name:
            raise ValueError(
                f"cpp function names must not contain ':': {name!r}"
            )
        self._name = name
        self._resources = resources
        self._max_retries = max_retries

    def options(self, **opts) -> "CppFunction":
        allowed = {"resources", "max_retries"}
        bad = set(opts) - allowed
        if bad:
            raise TypeError(
                f"cpp_function options support {sorted(allowed)}; "
                f"got {sorted(bad)}"
            )
        merged = {
            "resources": self._resources,
            "max_retries": self._max_retries,
            **opts,
        }
        return CppFunction(self._name, **merged)

    def remote(self, *args):
        """Submit; returns an ObjectRef whose value is the function's
        msgpack result decoded to plain Python data."""
        from ray_tpu import api

        out = api._runtime.run(
            api._runtime.core.submit_task(
                f"cfn:{self._name}",
                args,
                {},
                num_returns=1,
                resources=self._resources,
                max_retries=self._max_retries,
                runtime_env={"language": "cpp"},
            )
        )
        return out[0]


def cpp_function(name: str, **opts) -> CppFunction:
    """A handle to the C++ function registered as ``name`` in the
    cluster's configured worker binary (RAYTPU_REMOTE(name))."""
    return CppFunction(name, **opts)
