"""On-chip LLM serving benchmarks: paged decode throughput at real
batch sizes and prefill-interleave stall latency.

Run on a TPU chip (NOT CI — CI runs the interpreted kernel):

    python -m ray_tpu._private.llm_perf [--steps 50] [--json]

Measures, on the `bench` model (~430M, GQA 8/4):

1. **decode@64**: steady-state decode tokens/s at batch 64 with mixed
   sequence lengths, Pallas paged-attention kernel vs the XLA gather
   path. The gather path's HBM traffic scales with B x window x
   n_heads; the kernel's with the true page footprint x n_kv_heads
   (ops/pallas/paged_attention.py) — this prints the realized ratio.
2. **prefill stall**: per-decode-step wall times for an 8-request
   decode batch while a ~4k-token prompt is admitted mid-stream, with
   and without chunked prefill. Without chunking the admission step
   stalls every decode for the prompt's whole dense pass; with
   ``prefill_chunk`` the p99 step time stays near the chunk cost.

Floors are asserted here (not in CI: these are chip numbers). Rows are
appended to PERF.json by scripts/perf runs that pass --json.

(reference frame: vLLM's paged attention + chunked prefill, bought by
ray.llm via engine_kwargs — python/ray/llm/_internal/serve/.)
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _build_engine(use_kernel: bool, **kw):
    os.environ["RAY_TPU_PAGED_ATTN"] = "1" if use_kernel else "0"
    from ray_tpu.llm.engine import LLMEngine

    return LLMEngine(**kw)


def bench_attention_op_batch64(
    steps: int = 50, heads: "tuple[int, int]" = (8, 4),
    max_pages: int = 32, long_len: int = 2047, short_len: int = 256,
    long_every: int = 4,
) -> dict:
    """Op-level paged attention at batch 64, mixed true lengths —
    amortized loop timing (per-step host sync on this rig pays a
    ~200 ms tunnel RTT that would swamp the op; the engine rows below
    carry that caveat). ``heads`` = (n_heads, n_kv_heads): the bench
    model's (8, 4) and llama-8B's (32, 8) — the gather path's repeat
    factor n_heads/n_kv_heads is what the kernel's GQA blocking
    removes, so the speedup grows with it."""
    import time

    import jax
    import jax.numpy as jnp
    from functools import partial

    from ray_tpu.ops.pallas.paged_attention import paged_attention

    rng = np.random.default_rng(0)
    H, Hkv = heads
    B, K, Dh, P = 64, 1, 128, 64
    maxp = max_pages
    npages = min(B * maxp, 4096)
    q = jnp.asarray(rng.normal(size=(B, K, H, Dh)), jnp.bfloat16)
    kp = jnp.asarray(
        rng.normal(size=(npages, Hkv, P, Dh)), jnp.bfloat16
    )
    vp = jnp.asarray(
        rng.normal(size=(npages, Hkv, P, Dh)), jnp.bfloat16
    )
    lens = np.where(
        np.arange(B) % long_every == 0, long_len, short_len
    )
    tables = np.full((B, maxp), -1, np.int32)
    nxt = 1
    for bi in range(B):
        need = (lens[bi] + 1 + P - 1) // P
        tables[bi, :need] = np.arange(nxt, nxt + need) % npages
        nxt += need
    positions = jnp.asarray(lens, jnp.int32)
    tables_j = jnp.asarray(tables)

    kern = partial(paged_attention, n_kv_heads=Hkv)

    # The gather baseline is the REAL fallback body (one source of
    # truth in paged_kv): the benchmark measures the code path the
    # engine actually runs, not a private re-implementation.
    from ray_tpu.llm.paged_kv import _gather_page_attention
    from ray_tpu.models.llama import LlamaConfig

    # head_dim is d_model // n_heads: pin d_model so it comes out Dh.
    cfg = LlamaConfig(
        d_model=H * Dh, n_heads=H, n_kv_heads=Hkv, dtype=jnp.bfloat16
    )

    @jax.jit
    def gather_path(q, kp, vp, tables, positions):
        window = maxp * P
        pos2d = positions[:, None] + jnp.arange(K)[None, :]
        mask = jnp.arange(window)[None, None, :] > pos2d[:, :, None]
        return _gather_page_attention(
            q, kp, vp, jnp.maximum(tables, 0), mask, cfg
        )

    def timeit(f):
        # Warm with a SHORT LOOP, not one call: the first sustained
        # dispatch burst in a process pays ~15 ms of one-time overhead
        # that a single warm-up call does not absorb (measured — it
        # inflated whichever variant ran first by up to 6x).
        for _ in range(6):
            r = f(q, kp, vp, tables_j, positions)
        # axon gotcha: block_until_ready is unreliable — force sync
        # with a host transfer.
        float(jnp.sum(r.astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(steps):
            r = f(q, kp, vp, tables_j, positions)
        float(jnp.sum(r.astype(jnp.float32)))
        return (time.perf_counter() - t0) / steps

    tk = timeit(kern)
    tx = timeit(gather_path)
    return {
        "kernel_us": tk * 1e6,
        "gather_us": tx * 1e6,
        "speedup": tx / tk,
    }


def bench_decode_batch64(params, steps: int = 50) -> dict:
    from ray_tpu.llm.engine import SamplingParams
    from ray_tpu.models.llama import PRESETS

    cfg = PRESETS["bench"]
    B, max_seq, P = 64, 2048, 64
    rng = np.random.default_rng(0)
    # Mixed true lengths: a quarter long, the rest short — the shape
    # where per-slot length early-exit matters.
    lens = [1500 if i % 4 == 0 else 128 for i in range(B)]
    prompts = [
        rng.integers(1, cfg.vocab_size, size=n).tolist() for n in lens
    ]
    out = {}
    for label, use_kernel in (("kernel", True), ("gather", False)):
        eng = _build_engine(
            use_kernel,
            model=cfg, params=params, max_batch=B, max_seq=max_seq,
            kv="paged", page_size=P,
            num_pages=(B * max_seq) // P,
        )
        sp = SamplingParams(max_tokens=steps + 16)
        for p in prompts:
            eng.add_request(p, sp)
        while len(eng._active) < B:  # admit + prefill everyone
            eng.step()
        eng.step()  # one compiled-warm decode step
        t0 = time.perf_counter()
        for _ in range(steps):
            eng.step()
        dt = time.perf_counter() - t0
        out[label] = {
            "steps_per_s": steps / dt,
            "tok_per_s": steps * B / dt,
            "ms_per_step": dt / steps * 1e3,
        }
    out["speedup"] = (
        out["kernel"]["tok_per_s"] / out["gather"]["tok_per_s"]
    )
    return out


def bench_prefill_stall(params, chunk: int = 1024) -> dict:
    from ray_tpu.llm.engine import SamplingParams
    from ray_tpu.models.llama import PRESETS

    # An 8k prompt: long enough that the monolithic prefill's compute
    # dominates the rig's ~200 ms dispatch RTT, so the stall (and the
    # chunking win) is visible through the tunnel noise.
    cfg = PRESETS["bench"]
    B, max_seq, P = 9, 8192, 64
    rng = np.random.default_rng(1)
    decode_prompts = [
        rng.integers(1, cfg.vocab_size, size=64).tolist()
        for _ in range(B - 1)
    ]
    long_prompt = rng.integers(1, cfg.vocab_size, size=7936).tolist()
    out = {}
    for label, use_chunk in (("chunked", True), ("monolithic", False)):
        eng = _build_engine(
            True,
            model=cfg, params=params, max_batch=B, max_seq=max_seq,
            kv="paged", page_size=P,
            prefill_chunk=chunk if use_chunk else None,
        )
        sp = SamplingParams(max_tokens=512)
        for p in decode_prompts:
            eng.add_request(p, sp)
        while len(eng._active) < B - 1:
            eng.step()
        for _ in range(4):  # warm the decode program
            eng.step()
        # Warm the prefill program shapes out-of-band so the measured
        # stall is execution, not first-compile.
        warm = rng.integers(1, cfg.vocab_size, size=7935).tolist()
        eng.add_request(warm, SamplingParams(max_tokens=1))
        for _ in range(12):
            eng.step()
        # Admit the long prompt mid-stream and time every step until
        # it activates plus a tail of plain decode steps.
        eng.add_request(long_prompt, sp)
        times = []
        for _ in range(16):
            t0 = time.perf_counter()
            eng.step()
            times.append(time.perf_counter() - t0)
        times_ms = np.asarray(times) * 1e3
        out[label] = {
            "p50_ms": float(np.percentile(times_ms, 50)),
            "p99_ms": float(np.percentile(times_ms, 99)),
            "max_ms": float(times_ms.max()),
        }
    out["stall_ratio_p99"] = (
        out["monolithic"]["p99_ms"] / out["chunked"]["p99_ms"]
    )
    return out


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import jax

    assert jax.default_backend() == "tpu", (
        "llm_perf measures chip numbers; run on TPU"
    )
    from ray_tpu.models.llama import PRESETS, init_params

    params = init_params(jax.random.key(0), PRESETS["bench"])
    op_bench = bench_attention_op_batch64(steps=args.steps)
    op_8b = bench_attention_op_batch64(
        steps=args.steps, heads=(32, 8)
    )
    # Long-context serving shape: an 8k-token table width with mostly
    # short true lengths — where the kernel's per-slot early-exit pays
    # (the gather path must materialize the FULL window per slot).
    op_wide = bench_attention_op_batch64(
        steps=args.steps, heads=(32, 8), max_pages=128,
        long_len=7000, short_len=300, long_every=8,
    )
    decode = bench_decode_batch64(params, steps=args.steps)
    decode["tunnel_bound"] = True  # per-step host sync pays the rig's
    # ~200 ms dispatch RTT in BOTH paths; op rows above are the clean
    # attention comparison.
    stall = bench_prefill_stall(params)
    results = {
        "paged_attention_op@64_h8kv4": op_bench,
        "paged_attention_op@64_h32kv8": op_8b,
        "paged_attention_op@64_8k_window": op_wide,
        "decode@64": decode,
        "prefill_stall": stall,
    }

    # Floors. After the round-5 einsum-folded fallback rewrite (GQA-
    # grouped q, no materialized window transpose or head repeat), the
    # XLA gather path itself is ~4-5x faster than round 4's (17.4 ->
    # 4.6 ms at 32/8 heads), so the kernel's RELATIVE edge at this
    # window size is 1.1-1.3x (its in-place page reads avoid
    # materializing the gathered window, which matters more at wider
    # tables). Floors therefore gate against INVERSION (kernel slower
    # than fallback) plus absolute regressions of either path; the
    # engine rows are tunnel-RTT-dominated on this rig, and the
    # chunked-prefill p99 must beat the monolithic stall.
    assert op_bench["speedup"] > 0.9, op_bench
    assert op_8b["speedup"] > 1.0, op_8b
    assert op_bench["kernel_us"] < 8000, op_bench
    # Absolute fallback bounds: speedup alone would PASS if the
    # einsum-folded fallback regressed (a slower gather inflates the
    # ratio). r4's fallback was ~7ms at 8/4 and ~17-21ms at 32/8.
    assert op_bench["gather_us"] < 6500, op_bench
    assert op_8b["gather_us"] < 9000, op_8b
    # The wide-window case is where the kernel's early-exit must win
    # decisively (measured ~2.1x on v5e).
    assert op_wide["speedup"] > 1.5, op_wide
    # Engine-level the two paths are now EQUIVALENT through the tunnel
    # (~0.95-1.4x run to run): guard only against a real inversion.
    assert decode["speedup"] > 0.8, decode
    assert stall["stall_ratio_p99"] > 1.3, stall
    print(json.dumps(results, indent=None if args.json else 2))
    return results


if __name__ == "__main__":
    main()
