"""TPU301 — broad-except hygiene.

``except Exception: pass`` inside an RPC handler or daemon loop is how
poison flags, death fan-out, and drain notices get silently eaten
(PR 1's fault model assumes failures PROPAGATE). A broad handler is
fine if it re-raises, logs the exception, or carries an explicit
``# tpulint: allow(broad-except reason=…)`` pragma stating why
swallowing is the intent.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name

_BROAD = ("Exception", "BaseException")
_LOG_METHODS = frozenset({
    "exception", "warning", "error", "critical", "info", "debug", "log",
})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


def _is_log_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    if name.endswith("print_exc") or name == "warnings.warn":
        return True
    head, _, method = name.rpartition(".")
    return method in _LOG_METHODS and "log" in head.lower()


def _handles(handler: ast.ExceptHandler) -> bool:
    """True if the handler body re-raises or logs."""
    for node in _walk_body(handler.body):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _is_log_call(node):
            return True
    return False


def _walk_body(body):
    """ast.walk over statements, NOT descending into nested function
    definitions — a `raise` inside a callback defined in the handler
    does not make the handler itself re-raise."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _Visitor(ScopeVisitor):
    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if _is_broad(node) and not _handles(node):
            what = (
                "bare `except`" if node.type is None
                else "`except Exception`"
            )
            self.ctx.report(
                "TPU301", node,
                f"{what} neither re-raises nor logs — a swallowed "
                "failure here can mask death fan-out / poison flags; "
                "log it, narrow to a typed exception, or pragma with "
                "a reason",
                scope=self.scope,
            )
        self.generic_visit(node)


def run(ctx: FileContext):
    if "except" not in ctx.source:
        return None
    _Visitor(ctx).visit(ctx.tree)
    return None


def finalize(states):
    return []
