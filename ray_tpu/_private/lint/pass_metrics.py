"""TPU401/TPU402/TPU403 — metrics & span hygiene.

- TPU401: ``Counter``/``Gauge``/``Histogram`` constructed inside a
  function. The registry now tolerates re-registration (same shape
  returns the live instance) but every call still pays lock + shape
  verification on a hot path, and a tag/shape drift turns into a
  runtime ValueError at the call site instead of import time. Metrics
  belong at module scope.
- TPU402: a span context manager (``tracing.span``/``thread_trace``/
  ``activate``/``train.step_span``/``jax_profile``) called bare —
  without ``with`` or ``enter_context(...)`` — constructs the CM and
  drops it unentered: the span silently never records.
- TPU403: unbounded-cardinality metric labels — a request/session/
  trace id or uuid-shaped value used as a metric tag. Every distinct
  label value is a new time series held forever by the registry and
  shipped on every scrape; one busy serve deployment tagged by
  request_id is an OOM with a delay fuse. Per-request identity belongs
  on span attributes (ring-bounded), never on metric labels. Fires on
  metric constructors whose ``tag_keys`` name an id-shaped key, and on
  ``.inc/.set/.observe(..., tags={...})`` calls whose tag keys or
  values are id-shaped (including uuid calls, f-strings and str()/
  subscript wrappers around id-shaped names).
"""

from __future__ import annotations

import ast
import re

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name

_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
# Identifier fragments that signal per-request/per-session cardinality.
_UNBOUNDED_RE = re.compile(
    r"request[_-]?id|session[_-]?id|trace[_-]?id|span[_-]?id|"
    r"correlation[_-]?id|task[_-]?id|uuid|guid",
    re.IGNORECASE,
)
_METRIC_METHODS = frozenset({"inc", "set", "observe"})
_SPAN_CMS = frozenset({
    "span", "step_span", "thread_trace", "activate", "jax_profile",
})
_SPAN_RECEIVERS = ("tracing", "train", "telemetry", "trace")


def _metric_ctor(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name) and func.id in _METRIC_CTORS:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_CTORS:
        recv = dotted_name(func.value)
        if recv and "metric" in recv.split(".")[-1].lower():
            return func.attr
    return None


def _unbounded_expr(node: ast.AST, depth: int = 0) -> str | None:
    """A human-readable description of why ``node`` smells like an
    unbounded id, or None. Unwraps the idioms ids hide in: uuid calls,
    str()/format() coercion, f-strings, and `[:16]`-style slicing."""
    if depth > 4 or node is None:
        return None
    name = dotted_name(node)
    if name and _UNBOUNDED_RE.search(name.split(".")[-1]):
        return name
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if "uuid" in fname.lower():
            return f"{fname}(...)"
        if isinstance(node.func, ast.Name) and node.func.id in (
            "str", "repr", "format"
        ):
            for arg in node.args:
                hit = _unbounded_expr(arg, depth + 1)
                if hit:
                    return hit
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "format", "hex", "lower", "upper", "strip"
        ):
            hit = _unbounded_expr(node.func.value, depth + 1)
            if hit:
                return hit
            for arg in node.args:
                hit = _unbounded_expr(arg, depth + 1)
                if hit:
                    return hit
    if isinstance(node, ast.JoinedStr):
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                hit = _unbounded_expr(v.value, depth + 1)
                if hit:
                    return hit
    if isinstance(node, ast.Subscript):
        return _unbounded_expr(node.value, depth + 1)
    if isinstance(node, ast.Attribute):
        # dotted_name already failed (call/subscript in the chain):
        # inspect the final attribute name, then whatever it hangs off
        # (uuid.uuid4().hex reaches here as Attribute-over-Call).
        if _UNBOUNDED_RE.search(node.attr):
            return node.attr
        return _unbounded_expr(node.value, depth + 1)
    return None


def _tag_keys_hit(call: ast.Call) -> str | None:
    """An id-shaped string constant inside a ctor's tag_keys=(...)."""
    for kw in call.keywords:
        if kw.arg != "tag_keys":
            continue
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            for elt in kw.value.elts:
                if (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)
                    and _UNBOUNDED_RE.search(elt.value)
                ):
                    return elt.value
    return None


def _tags_dict_hit(call: ast.Call) -> str | None:
    """An id-shaped key or value inside a record call's tags={...}."""
    for kw in call.keywords:
        if kw.arg != "tags" or not isinstance(kw.value, ast.Dict):
            continue
        for k, v in zip(kw.value.keys, kw.value.values):
            if (
                isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and _UNBOUNDED_RE.search(k.value)
            ):
                return f"key {k.value!r}"
            hit = _unbounded_expr(v)
            if hit:
                return f"value `{hit}`"
    return None


def _span_cm(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _SPAN_CMS:
        recv = dotted_name(func.value)
        last = recv.split(".")[-1].lower() if recv else ""
        if any(h in last for h in _SPAN_RECEIVERS):
            return f"{recv}.{func.attr}"
    elif isinstance(func, ast.Name) and func.id in ("step_span",
                                                    "thread_trace"):
        return func.id
    return None


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        # Call nodes that ARE properly entered: with-items and
        # enter_context(...) arguments.
        self._entered: set[int] = set()
        manually_entered: set[str] = set()
        for node in ctx.nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    self._entered.add(id(item.context_expr))
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name.endswith("enter_context"):
                    for arg in node.args:
                        self._entered.add(id(arg))
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "__enter__"
                        and isinstance(node.func.value, ast.Name)):
                    manually_entered.add(node.func.value.id)
        # `s = tracing.span(...)` followed by `s.__enter__()` IS
        # entered — whether the pairing balances on every path is
        # TPU404's (path-sensitive) question, not TPU402's.
        for node in ctx.nodes:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id in manually_entered
                    and isinstance(node.value, ast.Call)):
                self._entered.add(id(node.value))

    def visit_Call(self, node: ast.Call):
        ctor = _metric_ctor(node)
        if ctor is not None and self.in_function:
            self.ctx.report(
                "TPU401", node,
                f"`{ctor}` constructed inside a function: registry "
                "lookup + shape check on every call, and shape drift "
                "becomes a runtime error here instead of import time — "
                "hoist to module scope",
                scope=self.scope,
            )
        if ctor is not None:
            hit = _tag_keys_hit(node)
            if hit is not None:
                self.ctx.report(
                    "TPU403", node,
                    f"`{ctor}` declares id-shaped tag key {hit!r}: "
                    "every distinct value is a permanent time series "
                    "(unbounded cardinality) — put per-request identity "
                    "on span attributes, not metric labels",
                    scope=self.scope,
                )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_METHODS
        ):
            hit = _tags_dict_hit(node)
            if hit is not None:
                self.ctx.report(
                    "TPU403", node,
                    f"metric `.{node.func.attr}()` tagged with "
                    f"id-shaped {hit}: every distinct value is a "
                    "permanent time series (unbounded cardinality) — "
                    "put per-request identity on span attributes, not "
                    "metric labels",
                    scope=self.scope,
                )
        cm = _span_cm(node)
        if cm is not None and id(node) not in self._entered:
            self.ctx.report(
                "TPU402", node,
                f"`{cm}(...)` called without `with` (or "
                "`enter_context`): the context manager is never "
                "entered, so the span never records",
                scope=self.scope,
            )
        self.generic_visit(node)


_GATE_TOKENS = ("Counter", "Gauge", "Histogram", "span(", "thread_trace",
                "activate(", "jax_profile(", "tags")


def run(ctx: FileContext):
    # Every reportable shape carries one of these tokens textually:
    # metric ctors their class name, span CMs their method name plus
    # the opening paren of the call, and the .inc/.set/.observe label
    # check its `tags=` keyword.
    if not any(t in ctx.source for t in _GATE_TOKENS):
        return None
    _Visitor(ctx).visit(ctx.tree)
    return None


def finalize(states):
    return []
