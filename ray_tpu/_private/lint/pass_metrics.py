"""TPU401/TPU402 — metrics & span hygiene.

- TPU401: ``Counter``/``Gauge``/``Histogram`` constructed inside a
  function. The registry now tolerates re-registration (same shape
  returns the live instance) but every call still pays lock + shape
  verification on a hot path, and a tag/shape drift turns into a
  runtime ValueError at the call site instead of import time. Metrics
  belong at module scope.
- TPU402: a span context manager (``tracing.span``/``thread_trace``/
  ``activate``/``train.step_span``/``jax_profile``) called bare —
  without ``with`` or ``enter_context(...)`` — constructs the CM and
  drops it unentered: the span silently never records.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name

_METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
_SPAN_CMS = frozenset({
    "span", "step_span", "thread_trace", "activate", "jax_profile",
})
_SPAN_RECEIVERS = ("tracing", "train", "telemetry", "trace")


def _metric_ctor(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name) and func.id in _METRIC_CTORS:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in _METRIC_CTORS:
        recv = dotted_name(func.value)
        if recv and "metric" in recv.split(".")[-1].lower():
            return func.attr
    return None


def _span_cm(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _SPAN_CMS:
        recv = dotted_name(func.value)
        last = recv.split(".")[-1].lower() if recv else ""
        if any(h in last for h in _SPAN_RECEIVERS):
            return f"{recv}.{func.attr}"
    elif isinstance(func, ast.Name) and func.id in ("step_span",
                                                    "thread_trace"):
        return func.id
    return None


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        # Call nodes that ARE properly entered: with-items and
        # enter_context(...) arguments.
        self._entered: set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    self._entered.add(id(item.context_expr))
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name.endswith("enter_context"):
                    for arg in node.args:
                        self._entered.add(id(arg))

    def visit_Call(self, node: ast.Call):
        ctor = _metric_ctor(node)
        if ctor is not None and self.in_function:
            self.ctx.report(
                "TPU401", node,
                f"`{ctor}` constructed inside a function: registry "
                "lookup + shape check on every call, and shape drift "
                "becomes a runtime error here instead of import time — "
                "hoist to module scope",
                scope=self.scope,
            )
        cm = _span_cm(node)
        if cm is not None and id(node) not in self._entered:
            self.ctx.report(
                "TPU402", node,
                f"`{cm}(...)` called without `with` (or "
                "`enter_context`): the context manager is never "
                "entered, so the span never records",
                scope=self.scope,
            )
        self.generic_visit(node)


def run(ctx: FileContext):
    _Visitor(ctx).visit(ctx.tree)
    return None


def finalize(states):
    return []
