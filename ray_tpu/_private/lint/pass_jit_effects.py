"""TPU602 — trace-time side effect under jit.

A ``@jax.jit`` function's Python body runs ONCE, at trace time. A
metric increment, a log line, a ``memory.track`` claim, or an append to
a closure list inside it does not "run every step" — it runs exactly
once per compilation and then silently lies forever: the counter stays
flat while the program runs a million steps, the log says the branch
executed when only its traced residue did. Flagged shapes:

- ``print(...)`` / ``logger.info(...)`` / ``logging.warning(...)`` /
  ``warnings.warn(...)``
- tracing/metric emission: ``emit_span`` / ``record_span`` /
  ``record_op``, ``.inc()`` / ``.observe()`` on a dotted receiver, and
  ``.set()`` on an UPPERCASE receiver (the module-level metric-constant
  convention — ``x.at[i].set(v)``, jax's functional update, has a
  Subscript receiver and never matches)
- ``memory.track(...)`` ledger claims
- ``closure_list.append(...)`` where the list is not local to the
  jitted function — the appended tracer leaks out of the trace

The legitimate escape hatches stay silent: ``jax.debug.print`` /
``jax.debug.callback`` / ``io_callback`` / ``pure_callback`` run at
execution time by design, and a function passed INTO them is never
walked (only direct calls in the traced body are).

Scope: jit-decorated defs, functions wrapped by a ``jit(...)`` call in
the same file, and — resolved in ``finalize`` against the program-wide
function table — functions ``jit()``-wrapped FROM ANOTHER FILE (the
ZeRO step layout: ``step.py`` defines the body, the trainer wraps it).
The recompile sanitizer remains the runtime backstop for layouts the
import-map unification can't see (dynamic dispatch, getattr)."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import jit_util
from ray_tpu._private.lint.core import FileContext, dotted_name, iter_tree

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
})
_TRACE_VERBS = frozenset({"emit_span", "record_span", "record_op"})
_CALLBACK_TAILS = frozenset({
    "io_callback", "pure_callback", "callback", "debug_callback",
})


def _side_effect(call: ast.Call, local_names: set[str],
                 params: set[str]) -> str | None:
    """A human-readable description when ``call`` is a trace-time side
    effect, else None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "print":
            return "print(...)"
        if func.id in _TRACE_VERBS:
            return f"{func.id}(...) span emission"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    recv = dotted_name(func.value)
    recv_tail = recv.split(".")[-1] if recv else ""
    head = recv.split(".")[0] if recv else ""
    if head == "jax":
        return None  # jax.debug.print and friends are execution-time
    if func.attr in _LOG_METHODS and (
            "log" in recv_tail.lower() or recv == "warnings"):
        return f"{recv}.{func.attr}(...) logging"
    if func.attr in _TRACE_VERBS:
        return f"{recv}.{func.attr}(...) span emission"
    if func.attr in ("inc", "observe") and recv:
        return f"{recv}.{func.attr}(...) metric update"
    if func.attr == "set" and recv_tail and recv_tail.isupper():
        return f"{recv}.set(...) metric update"
    if func.attr == "track" and ("mem" in recv_tail.lower()
                                 or recv_tail == "memory"):
        return f"{recv}.track(...) memory-ledger claim"
    if func.attr == "append" and isinstance(func.value, ast.Name):
        name = func.value.id
        if name not in local_names and name not in params:
            return (f"append to closure/global list `{name}`: the "
                    "traced value leaks out of the trace")
    return None


def _local_stores(fn_node) -> set[str]:
    out: set[str] = set()
    for node in iter_tree(fn_node):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in iter_tree(node.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _walk_traced(fn_node):
    """Yield Call nodes in the traced body: skip nested def/lambda
    bodies only when they are ARGUMENTS to a callback wrapper (they run
    at execution time); a plain nested helper def still traces when
    called, so its body is walked."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            tail = fname.split(".")[-1] if fname else ""
            if tail in _CALLBACK_TAILS:
                # Walk only the non-callable args (shapes, operands).
                for arg in node.args:
                    if not isinstance(arg, (ast.Lambda, ast.Name)):
                        stack.append(arg)
                continue
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_function(ctx: FileContext, info, qual: str,
                    via: str | None = None) -> None:
    """Walk one traced function's body for trace-time side effects and
    report them against ITS file (pragmas land where the code is)."""
    params = set(info.params)
    local_names = _local_stores(info.node)
    scope = (f"{info.class_name}.{info.node.name}"
             if info.class_name else info.node.name)
    origin = f" (jit()-wrapped in {via})" if via else ""
    for call in _walk_traced(info.node):
        desc = _side_effect(call, local_names, params)
        if desc is not None:
            ctx.report(
                "TPU602", call,
                f"{desc} inside jit-traced `{qual}`{origin}: this runs "
                "ONCE at trace time, not per step — the compiled "
                "program carries no trace of it and the signal it "
                "claims to emit silently flatlines. Hoist it to "
                "the caller or route it through jax.debug/"
                "io_callback",
                scope=scope,
            )


class _PassState:
    def __init__(self, ctx: FileContext, ji, checked: set[str]):
        self.ctx = ctx
        self.ji = ji
        self.checked = checked


def run(ctx: FileContext):
    # Files WITHOUT any jit token still contribute their function table
    # to finalize: a side-effectful helper defined here may be
    # jit()-wrapped from another file entirely.
    ji = jit_util.jit_index(ctx)
    checked: set[str] = set()
    if "jit" in ctx.source:
        traced = set(ji.jit_defs) | (ji.wrapped & set(ji.mi.functions))
        for qual in sorted(traced):
            _check_function(ctx, ji.mi.functions[qual], qual)
            checked.add(qual)
    return _PassState(ctx, ji, checked)


def finalize(states):
    """Cross-file closure: a function jit()-wrapped in module A but
    DEFINED in module B is walked in B's context (module-local run()
    can't see it — the carried PR-13 blind spot)."""
    states = [st for st in states if st is not None]
    if len(states) < 2:
        return []
    # Program-wide function table (exact quals + bare-tail fallback,
    # the pass_donation unification).
    functions: dict[str, tuple] = {}
    by_tail: dict[str, tuple] = {}
    checked: set[str] = set()
    for st in states:
        checked |= st.checked
        for qual, info in st.ji.mi.functions.items():
            functions.setdefault(qual, (st.ctx, info))
            by_tail.setdefault(qual.split(".")[-1], (st.ctx, info, qual))
    done: set[tuple] = set()
    for st in states:
        for wrapped in sorted(st.ji.wrapped):
            if wrapped in st.ji.mi.functions:
                continue  # module-local: run() covered it
            rec = functions.get(wrapped)
            qual = wrapped
            if rec is None:
                tail_rec = by_tail.get(wrapped.split(".")[-1])
                if tail_rec is None:
                    continue
                rec = (tail_rec[0], tail_rec[1])
                qual = tail_rec[2]
            if qual in checked:
                continue
            ctx, info = rec
            key = (id(ctx), qual)
            if key in done:
                continue
            done.add(key)
            _check_function(ctx, info, qual, via=st.ctx.module)
    return []
