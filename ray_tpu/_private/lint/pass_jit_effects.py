"""TPU602 — trace-time side effect under jit.

A ``@jax.jit`` function's Python body runs ONCE, at trace time. A
metric increment, a log line, a ``memory.track`` claim, or an append to
a closure list inside it does not "run every step" — it runs exactly
once per compilation and then silently lies forever: the counter stays
flat while the program runs a million steps, the log says the branch
executed when only its traced residue did. Flagged shapes:

- ``print(...)`` / ``logger.info(...)`` / ``logging.warning(...)`` /
  ``warnings.warn(...)``
- tracing/metric emission: ``emit_span`` / ``record_span`` /
  ``record_op``, ``.inc()`` / ``.observe()`` on a dotted receiver, and
  ``.set()`` on an UPPERCASE receiver (the module-level metric-constant
  convention — ``x.at[i].set(v)``, jax's functional update, has a
  Subscript receiver and never matches)
- ``memory.track(...)`` ledger claims
- ``closure_list.append(...)`` where the list is not local to the
  jitted function — the appended tracer leaks out of the trace

The legitimate escape hatches stay silent: ``jax.debug.print`` /
``jax.debug.callback`` / ``io_callback`` / ``pure_callback`` run at
execution time by design, and a function passed INTO them is never
walked (only direct calls in the traced body are).

Scope is module-local: jit-decorated defs plus functions wrapped by a
``jit(...)`` call in the same file (the overwhelmingly common layout
here). The recompile sanitizer is the runtime backstop for the rest.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint import jit_util
from ray_tpu._private.lint.core import FileContext, dotted_name

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
})
_TRACE_VERBS = frozenset({"emit_span", "record_span", "record_op"})
_CALLBACK_TAILS = frozenset({
    "io_callback", "pure_callback", "callback", "debug_callback",
})


def _side_effect(call: ast.Call, local_names: set[str],
                 params: set[str]) -> str | None:
    """A human-readable description when ``call`` is a trace-time side
    effect, else None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "print":
            return "print(...)"
        if func.id in _TRACE_VERBS:
            return f"{func.id}(...) span emission"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    recv = dotted_name(func.value)
    recv_tail = recv.split(".")[-1] if recv else ""
    head = recv.split(".")[0] if recv else ""
    if head == "jax":
        return None  # jax.debug.print and friends are execution-time
    if func.attr in _LOG_METHODS and (
            "log" in recv_tail.lower() or recv == "warnings"):
        return f"{recv}.{func.attr}(...) logging"
    if func.attr in _TRACE_VERBS:
        return f"{recv}.{func.attr}(...) span emission"
    if func.attr in ("inc", "observe") and recv:
        return f"{recv}.{func.attr}(...) metric update"
    if func.attr == "set" and recv_tail and recv_tail.isupper():
        return f"{recv}.set(...) metric update"
    if func.attr == "track" and ("mem" in recv_tail.lower()
                                 or recv_tail == "memory"):
        return f"{recv}.track(...) memory-ledger claim"
    if func.attr == "append" and isinstance(func.value, ast.Name):
        name = func.value.id
        if name not in local_names and name not in params:
            return (f"append to closure/global list `{name}`: the "
                    "traced value leaks out of the trace")
    return None


def _local_stores(fn_node) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _walk_traced(fn_node):
    """Yield Call nodes in the traced body: skip nested def/lambda
    bodies only when they are ARGUMENTS to a callback wrapper (they run
    at execution time); a plain nested helper def still traces when
    called, so its body is walked."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            tail = fname.split(".")[-1] if fname else ""
            if tail in _CALLBACK_TAILS:
                # Walk only the non-callable args (shapes, operands).
                for arg in node.args:
                    if not isinstance(arg, (ast.Lambda, ast.Name)):
                        stack.append(arg)
                continue
            yield node
        stack.extend(ast.iter_child_nodes(node))


def run(ctx: FileContext):
    if "jit" not in ctx.source:
        return None
    ji = jit_util.jit_index(ctx)
    traced = set(ji.jit_defs) | (ji.wrapped & set(ji.mi.functions))
    if not traced:
        return None
    for qual in sorted(traced):
        info = ji.mi.functions[qual]
        params = set(info.params)
        local_names = _local_stores(info.node)
        scope = (f"{info.class_name}.{info.node.name}"
                 if info.class_name else info.node.name)
        for call in _walk_traced(info.node):
            desc = _side_effect(call, local_names, params)
            if desc is not None:
                ctx.report(
                    "TPU602", call,
                    f"{desc} inside jit-traced `{qual}`: this runs "
                    "ONCE at trace time, not per step — the compiled "
                    "program carries no trace of it and the signal it "
                    "claims to emit silently flatlines. Hoist it to "
                    "the caller or route it through jax.debug/"
                    "io_callback",
                    scope=scope,
                )
    return None


def finalize(states):
    return []
