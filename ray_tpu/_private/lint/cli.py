"""`ray_tpu lint` / `python -m ray_tpu._private.lint` — CLI.

Exit codes: 0 clean (or everything baselined), 1 new violations,
2 usage/IO error. `--update-baseline` rewrites the baseline from the
current tree and always exits 0.

`--changed [REF]` (pre-commit mode) lints only the files in `git diff
REF` (default HEAD, plus untracked files) — but ANALYZES their
import-graph neighbors too, so the interprocedural rules
(TPU103/TPU202/TPU204) still see helpers and lock owners defined in
unchanged files, plus the protocol-anchor files (RPC handler modules,
`config.py`, the journal restore) so the TPU70x contract rules always
judge a changed caller against the real handler table. Only
violations in changed files are reported.

`--strict` additionally reports call sites the protocol tier cannot
resolve statically (dynamic RPC method names); `--knob-docs` renders
the CONFIG_DEFS registry as markdown and exits.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import re
import subprocess
import sys
import time

from ray_tpu._private.lint import baseline as baseline_mod
from ray_tpu._private.lint import core

DEFAULT_BASELINE = "lint_baseline.json"

_IMPORT_RE = re.compile(
    r"^\s*(?:from\s+([\w\.]+)\s+import|import\s+([\w\.]+))", re.MULTILINE)


def _git(root: str, *args: str) -> list[str]:
    out = subprocess.run(
        ["git", "-C", root, *args],
        capture_output=True, text=True, timeout=30, check=True,
    )
    return [ln for ln in out.stdout.splitlines() if ln.strip()]


def _changed_files(paths: list[str], ref: str) -> tuple[list[str], str]:
    """Absolute paths of changed+untracked .py files under ``paths``,
    plus the git root. Raises CalledProcessError outside a repo."""
    probe = os.path.abspath(paths[0])
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    root = _git(probe, "rev-parse", "--show-toplevel")[0]
    rel = _git(root, "diff", "--name-only", ref, "--", "*.py")
    rel += _git(root, "ls-files", "--others", "--exclude-standard",
                "--", "*.py")
    roots = [os.path.abspath(p) for p in paths]
    out = []
    for r in rel:
        p = os.path.join(root, r)
        if not os.path.exists(p):
            continue  # deleted file
        ap = os.path.abspath(p)
        if any(ap == rt or ap.startswith(rt + os.sep) for rt in roots):
            out.append(ap)
    return sorted(set(out)), root


def _module_tail(path: str) -> str:
    base = os.path.basename(path)
    if base == "__init__.py":
        return os.path.basename(os.path.dirname(path))
    return base[:-3] if base.endswith(".py") else base


def _imported_tails(path: str) -> set[str]:
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except OSError:
        return set()
    tails = set()
    for m in _IMPORT_RE.finditer(src):
        mod = m.group(1) or m.group(2)
        tails.add(mod.split(".")[-1])
    return tails


DEFAULT_CHANGED_HOPS = 3


def _expand_neighbors(changed: list[str], paths: list[str],
                      excludes, hops: int = DEFAULT_CHANGED_HOPS
                      ) -> list[str]:
    """changed ∪ up to ``hops`` import-graph hops in both directions —
    the files whose symbols the interprocedural passes must see to
    judge the changed ones (and vice versa).

    TRANSITIVE (PR-12's caveat closed): a 2-hop helper chain
    ``caller → middle → issuer`` with an unchanged ``middle`` used to
    hide a TPU103/TPU601 from the pre-commit path, because one hop from
    ``caller`` never loaded ``issuer``'s definition. BFS over the
    undirected import graph, bounded (default 3 hops,
    ``--changed-hops=`` overrides) so one edit never degenerates into a
    full-tree analysis on a densely imported package."""
    tree = list(core.iter_python_files(paths, excludes=excludes))
    by_tail: dict[str, list[str]] = {}
    imports: dict[str, set[str]] = {}
    for f in tree:
        af = os.path.abspath(f)
        by_tail.setdefault(_module_tail(af), []).append(af)
        imports[af] = _imported_tails(af)
    out = set(changed)
    frontier = set(changed)
    for _ in range(max(0, hops)):
        if not frontier:
            break
        frontier_tails = {_module_tail(f) for f in frontier}
        nxt: set[str] = set()
        for f in tree:
            af = os.path.abspath(f)
            if af in out:
                continue
            # f imports a frontier module, or a frontier file imports f
            if imports[af] & frontier_tails:
                nxt.add(af)
                continue
            tail = _module_tail(af)
            if any(tail in imports[c] for c in frontier):
                nxt.add(af)
        out |= nxt
        frontier = nxt
    return sorted(out)


_HOOK_BODY = """\
#!/bin/sh
# tpulint pre-commit hook (installed by `ray_tpu lint --install-hook`).
# Lints only the files changed vs HEAD, expanding import-graph
# neighbors — plus the protocol anchors (RPC handler modules,
# config.py, the journal restore) — so the interprocedural rules and
# the TPU70x distributed-protocol tier stay sound. Bypass a single
# commit with `git commit --no-verify`.
exec {python} -m ray_tpu._private.lint {target} --changed
"""

# Files that DEFINE a distributed contract: RPC handler tables, the
# config registry, the journal replay. Always analyzed (never
# reported) in --changed mode — a changed caller must be judged
# against the real contract even when the defining file is far away
# in the import graph.
_ANCHOR_RE = re.compile(
    r"async def _on_\w+\(|CONFIG_DEFS\s*[:=]|def _restore_from_journal\(")


def _protocol_anchors(paths: list[str], excludes) -> set[str]:
    out: set[str] = set()
    for f in core.iter_python_files(paths, excludes=excludes):
        try:
            with open(f, encoding="utf-8") as fh:
                if _ANCHOR_RE.search(fh.read()):
                    out.add(os.path.abspath(f))
        except OSError:
            continue
    return out


def _install_hook(paths: list[str]) -> int:
    """Write .git/hooks/pre-commit running `lint --changed` over the
    first target path's repository."""
    probe = os.path.abspath(paths[0])
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    try:
        root = _git(probe, "rev-parse", "--show-toplevel")[0]
    except (subprocess.CalledProcessError, OSError,
            subprocess.TimeoutExpired) as e:
        print(f"error: --install-hook needs a git repo: {e}",
              file=sys.stderr)
        return 2
    hooks_dir = os.path.join(root, ".git", "hooks")
    os.makedirs(hooks_dir, exist_ok=True)
    hook = os.path.join(hooks_dir, "pre-commit")
    if os.path.exists(hook):
        print(f"error: {hook} already exists — remove it first (or "
              "chain scripts/pre-commit.sample from it)",
              file=sys.stderr)
        return 2
    target = os.path.relpath(os.path.abspath(paths[0]), root)
    with open(hook, "w", encoding="utf-8") as f:
        f.write(_HOOK_BODY.format(python=sys.executable, target=target))
    os.chmod(hook, 0o755)
    print(f"installed {hook}: runs `lint {target} --changed` per commit")
    return 0


def knob_docs_markdown() -> str:
    """The CONFIG_DEFS registry rendered as a markdown table — the
    generator behind README's "Config registry" appendix, kept here so
    docs and registry can never drift (TPU703's doc-drift sub-check
    closes the loop in the other direction)."""
    from ray_tpu._private import config

    def esc(text: str) -> str:
        return str(text).replace("|", "\\|")

    lines = [
        "## Config registry",
        "",
        "<!-- generated: python -m ray_tpu._private.lint --knob-docs -->",
        "",
        "Every knob resolves override → `RAY_TPU_<NAME>` env var → "
        "default (see `ray_tpu/_private/config.py`).",
        "",
        "| knob | type | default | doc |",
        "|---|---|---|---|",
    ]
    for name in sorted(config.CONFIG_DEFS):
        typ, default, doc = config.CONFIG_DEFS[name]
        lines.append(
            f"| `{name}` | {typ.__name__} | `{default!r}` | "
            f"{esc(' '.join(doc.split()))} |")
    return "\n".join(lines) + "\n"


def _find_default_baseline(paths: list[str]) -> str | None:
    """Look for lint_baseline.json next to / above the first target so
    `ray_tpu lint ray_tpu/` from the repo root just works."""
    probe = os.path.abspath(paths[0]) if paths else os.getcwd()
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    for _ in range(6):
        cand = os.path.join(probe, DEFAULT_BASELINE)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="ray_tpu lint",
        description="tpulint: ray_tpu-specific static analysis "
                    "(collective divergence, lock discipline, exception "
                    "hygiene, metric/span hygiene, RPC reentrancy)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to analyze (default: ray_tpu "
                        "package next to this install)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        "found upward from the first path; 'off' "
                        "disables)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current tree")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids/names to keep "
                        "(e.g. TPU301,lock-order)")
    p.add_argument("--relative-to", default=None,
                   help="report paths relative to this directory "
                        "(default: cwd)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files changed vs REF (default "
                        "HEAD) plus untracked files; their "
                        "import-graph neighbors are analyzed (not "
                        "reported) so interprocedural rules stay "
                        "sound — the fast pre-commit path")
    p.add_argument("--changed-hops", type=int,
                   default=DEFAULT_CHANGED_HOPS, metavar="N",
                   help="import-graph hops to expand around changed "
                        f"files (default {DEFAULT_CHANGED_HOPS}): "
                        "helpers-of-helpers N levels deep stay "
                        "visible to the interprocedural rules")
    p.add_argument("--install-hook", action="store_true",
                   help="write .git/hooks/pre-commit running "
                        "`lint --changed` against the staged tree, "
                        "then exit")
    p.add_argument("--strict", action="store_true",
                   help="also report protocol call sites that cannot "
                        "be resolved statically (dynamic RPC method "
                        "names) — covered at runtime by the contract "
                        "sanitizer instead")
    p.add_argument("--knob-docs", action="store_true", dest="knob_docs",
                   help="render the CONFIG_DEFS registry (name, env "
                        "var, type, default, doc) as markdown and exit")
    args = p.parse_args(argv)

    if args.knob_docs:
        print(knob_docs_markdown(), end="")
        return 0

    paths = args.paths
    if not paths:
        # The package we live in — `ray_tpu lint` bare lints the install.
        paths = [os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))]
    for path in paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2

    if args.install_hook:
        return _install_hook(paths)

    rel = args.relative_to or os.getcwd()
    t0 = time.monotonic()
    report_only: set[str] | None = None
    n_changed = n_analyzed = None
    if args.changed is not None:
        try:
            changed, _git_root = _changed_files(paths, args.changed)
        except (subprocess.CalledProcessError, OSError,
                subprocess.TimeoutExpired) as e:
            print(f"error: --changed needs git: {e}", file=sys.stderr)
            return 2
        if not changed:
            print("tpulint: no changed .py files; nothing to lint",
                  file=sys.stderr)
            return 0
        analyze = _expand_neighbors(changed, paths,
                                    core.DEFAULT_EXCLUDES,
                                    hops=args.changed_hops)
        anchors = _protocol_anchors(paths, core.DEFAULT_EXCLUDES)
        analyze = sorted(set(analyze) | anchors)
        report_only = {os.path.abspath(c) for c in changed}
        n_changed, n_analyzed = len(changed), len(analyze)
        paths = analyze
    violations, errors = core.analyze_paths(paths, relative_to=rel,
                                            strict=args.strict)
    elapsed = time.monotonic() - t0

    if report_only is not None:
        violations = [
            v for v in violations
            if os.path.abspath(os.path.join(rel, v.path)) in report_only
        ]

    if args.select:
        keep = {t.strip() for t in args.select.split(",") if t.strip()}
        violations = [v for v in violations
                      if v.rule in keep or v.name in keep]

    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = _find_default_baseline(paths)
    elif baseline_path == "off":
        baseline_path = None

    if args.update_baseline:
        out_path = baseline_path or DEFAULT_BASELINE
        data = baseline_mod.make_baseline(violations)
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out_path}: {len(violations)} pinned violation(s) "
              f"across {len(data['entries'])} fingerprint(s)")
        return 0

    stale: list[str] = []
    reported = violations
    if baseline_path:
        try:
            base = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        reported, stale = baseline_mod.diff_against_baseline(
            violations, base)

    if args.as_json:
        out = {
            "violations": [v.to_dict() for v in reported],
            "total_found": len(violations),
            "baseline": baseline_path,
            "baselined": len(violations) - len(reported),
            "stale_baseline_entries": stale,
            "parse_errors": [
                {"path": p_, "error": e} for p_, e in errors],
            "elapsed_s": round(elapsed, 3),
        }
        if n_changed is not None:
            out["changed"] = {
                "ref": args.changed,
                "changed_files": n_changed,
                "analyzed_files": n_analyzed,
            }
        print(json.dumps(out, indent=2))
    else:
        for v in reported:
            print(v.format())
        for path_, err in errors:
            print(f"{path_}: parse error: {err}", file=sys.stderr)
        by_rule = collections.Counter(v.rule for v in reported)
        summary = ", ".join(
            f"{r}={n}" for r, n in sorted(by_rule.items())) or "none"
        pinned = len(violations) - len(reported)
        scope_note = ""
        if n_changed is not None:
            scope_note = (f" [--changed: {n_changed} changed, "
                          f"{n_analyzed} analyzed]")
        print(
            f"tpulint: {len(reported)} new violation(s) ({summary}); "
            f"{pinned} baselined; {elapsed:.2f}s{scope_note}",
            file=sys.stderr,
        )
        if stale:
            print(
                f"tpulint: {len(stale)} baseline entr"
                f"{'y is' if len(stale) == 1 else 'ies are'} stale "
                "(debt paid) — regenerate with --update-baseline to "
                "shrink the baseline",
                file=sys.stderr,
            )
    return 1 if reported else 0


if __name__ == "__main__":
    sys.exit(main())
