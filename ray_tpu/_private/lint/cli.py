"""`ray_tpu lint` / `python -m ray_tpu._private.lint` — CLI.

Exit codes: 0 clean (or everything baselined), 1 new violations,
2 usage/IO error. `--update-baseline` rewrites the baseline from the
current tree and always exits 0.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import time

from ray_tpu._private.lint import baseline as baseline_mod
from ray_tpu._private.lint import core

DEFAULT_BASELINE = "lint_baseline.json"


def _find_default_baseline(paths: list[str]) -> str | None:
    """Look for lint_baseline.json next to / above the first target so
    `ray_tpu lint ray_tpu/` from the repo root just works."""
    probe = os.path.abspath(paths[0]) if paths else os.getcwd()
    if os.path.isfile(probe):
        probe = os.path.dirname(probe)
    for _ in range(6):
        cand = os.path.join(probe, DEFAULT_BASELINE)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="ray_tpu lint",
        description="tpulint: ray_tpu-specific static analysis "
                    "(collective divergence, lock discipline, exception "
                    "hygiene, metric/span hygiene, RPC reentrancy)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to analyze (default: ray_tpu "
                        "package next to this install)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        "found upward from the first path; 'off' "
                        "disables)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current tree")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids/names to keep "
                        "(e.g. TPU301,lock-order)")
    p.add_argument("--relative-to", default=None,
                   help="report paths relative to this directory "
                        "(default: cwd)")
    args = p.parse_args(argv)

    paths = args.paths
    if not paths:
        # The package we live in — `ray_tpu lint` bare lints the install.
        paths = [os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))]
    for path in paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2

    rel = args.relative_to or os.getcwd()
    t0 = time.monotonic()
    violations, errors = core.analyze_paths(paths, relative_to=rel)
    elapsed = time.monotonic() - t0

    if args.select:
        keep = {t.strip() for t in args.select.split(",") if t.strip()}
        violations = [v for v in violations
                      if v.rule in keep or v.name in keep]

    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = _find_default_baseline(paths)
    elif baseline_path == "off":
        baseline_path = None

    if args.update_baseline:
        out_path = baseline_path or DEFAULT_BASELINE
        data = baseline_mod.make_baseline(violations)
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out_path}: {len(violations)} pinned violation(s) "
              f"across {len(data['entries'])} fingerprint(s)")
        return 0

    stale: list[str] = []
    reported = violations
    if baseline_path:
        try:
            base = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        reported, stale = baseline_mod.diff_against_baseline(
            violations, base)

    if args.as_json:
        print(json.dumps({
            "violations": [v.to_dict() for v in reported],
            "total_found": len(violations),
            "baseline": baseline_path,
            "baselined": len(violations) - len(reported),
            "stale_baseline_entries": stale,
            "parse_errors": [
                {"path": p_, "error": e} for p_, e in errors],
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    else:
        for v in reported:
            print(v.format())
        for path_, err in errors:
            print(f"{path_}: parse error: {err}", file=sys.stderr)
        by_rule = collections.Counter(v.rule for v in reported)
        summary = ", ".join(
            f"{r}={n}" for r, n in sorted(by_rule.items())) or "none"
        pinned = len(violations) - len(reported)
        print(
            f"tpulint: {len(reported)} new violation(s) ({summary}); "
            f"{pinned} baselined; {elapsed:.2f}s",
            file=sys.stderr,
        )
        if stale:
            print(
                f"tpulint: {len(stale)} baseline entr"
                f"{'y is' if len(stale) == 1 else 'ies are'} stale "
                "(debt paid) — regenerate with --update-baseline to "
                "shrink the baseline",
                file=sys.stderr,
            )
    return 1 if reported else 0


if __name__ == "__main__":
    sys.exit(main())
