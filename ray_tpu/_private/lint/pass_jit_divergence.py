"""TPU605 — rank-dependent jit-boundary divergence.

The compiled-program twin of TPU103. In SPMD jax every participating
process must execute the SAME compiled program in the same order: the
collectives live INSIDE the program (psum/all_gather lowered into the
XLA graph), so a rank- or ``slice_label``-dependent branch that selects
*which* jitted function runs::

    if ctx.rank == 0:
        state, m = self._step_full(state, batch)
    else:
        state, m = self._step_light(state, batch)

deadlocks inside XLA itself — rank 0's program issues collectives rank
1's program never joins, and none of the PR-1 host-side deadlines can
see it (the hang is below the runtime). TPU103 cannot catch this: the
collective verbs are invisible, lowered into the compiled graph.

Flagged: a call to a known-jitted callable (module-local jit bind or
decorated def, a var bound from a jit FACTORY cross-file, or a
jit-wrapped function qual) under a rank-/slice-dependent branch.
Uniform-argument dispatch (every rank picks the same branch because the
predicate is replicated config, not rank identity) is the pragma'd
exception — the pass cannot prove replication."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow, jit_util
from ray_tpu._private.lint.core import FileContext, dotted_name
from ray_tpu._private.lint.pass_rank_flow import (
    _FLOW_TOKENS,
    _is_divergence_test,
)


class _State(dataflow.PathState):
    __slots__ = ("guards",)

    def __init__(self):
        self.guards: tuple = ()

    def fork(self):
        st = _State()
        st.guards = self.guards
        return st

    def merge(self, other):
        pass


class _Walker(dataflow.FlowWalker):
    def __init__(self, ctx: FileContext, ji: jit_util.ModuleJitIndex,
                 info: dataflow.FunctionInfo, st: "_PassState"):
        self.ctx = ctx
        self.ji = ji
        self.info = info
        self.st = st

    def _scope(self):
        if self.info.class_name:
            return f"{self.info.class_name}.{self.info.node.name}"
        return self.info.node.name

    def on_branch(self, test, state, taken):
        if _is_divergence_test(test):
            state.guards = state.guards + (test.lineno,)
            return True
        return None

    def on_branch_exit(self, token, state):
        if token and state.guards:
            state.guards = state.guards[:-1]

    def on_call(self, call, state):
        if not state.guards:
            return
        klass = self.info.class_name
        name = dotted_name(call.func)
        if not name:
            return
        info = self.ji.lookup_callable(call, klass)
        if info is not None:
            self._report(call, name, state.guards[-1])
            return
        callee = self.ji.mi.resolve_call(call, klass)
        if callee is not None and (callee in self.ji.jit_defs
                                   or callee in self.ji.wrapped):
            self._report(call, name, state.guards[-1])
            return
        # Var bound from a possibly-jit factory, or a call into a
        # foreign function that may be jit-wrapped elsewhere: defer.
        canon = self.ji.mi.qualify(name, klass)
        fac = self.ji.maybe_factory_vars.get(canon)
        if fac is not None:
            self.st.events.append((
                self.ctx, fac, name, call.lineno, state.guards[-1],
                self._scope()))

    def _report(self, call, name, guard_line):
        self.ctx.report(
            "TPU605", call,
            f"jitted `{name}` invoked under a rank-/slice-dependent "
            f"branch (guard at line {guard_line}): ranks compile and "
            "run DIFFERENT programs, and any collective lowered into "
            "them deadlocks inside XLA where no host-side deadline "
            "can see it — dispatch one program and branch on data "
            "inside it (lax.cond)",
            scope=self._scope(),
        )


class _PassState:
    def __init__(self, ji: jit_util.ModuleJitIndex):
        self.ji = ji
        self.mi = ji.mi
        # (ctx, factory_qual, display name, line, guard_line, scope)
        self.events: list[tuple] = []


def run(ctx: FileContext):
    src = ctx.source
    if "jit" not in src and not any(
            t in src.lower() for t in _FLOW_TOKENS):
        return None
    ji = jit_util.jit_index(ctx)
    st = _PassState(ji)
    if any(t in src.lower() for t in _FLOW_TOKENS):
        for info in ji.mi.functions.values():
            walker = _Walker(ctx, ji, info, st)
            walker.walk_function(info.node, _State())
    return st


def finalize(states):
    states = [st for st in states if st is not None]
    if not states:
        return []
    factories: set[str] = set()
    for st in states:
        factories.update(st.ji.factories)
    if not factories:
        return []
    by_tail = {q.split(".")[-1] for q in factories}
    seen: set[tuple] = set()
    for st in states:
        for ctx, fac, name, line, guard_line, scope in st.events:
            if fac not in factories and fac.split(
                    ".")[-1] not in by_tail:
                continue
            key = (id(ctx), line, name)
            if key in seen:
                continue
            seen.add(key)
            ctx.report(
                "TPU605", _FakeNode(line),
                f"jitted `{name}` (compiled by factory `{fac}`) "
                f"invoked under a rank-/slice-dependent branch (guard "
                f"at line {guard_line}): ranks run different compiled "
                "programs — collectives lowered into them deadlock "
                "inside XLA. Dispatch one program for every rank",
                scope=scope,
            )
    return []


class _FakeNode:
    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno: int, col: int = 0):
        self.lineno = lineno
        self.col_offset = col
