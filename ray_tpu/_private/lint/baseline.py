"""Baseline pinning: existing debt is recorded, only NEW violations fail.

The baseline maps violation fingerprints (rule|path|scope|snippet —
line-number-free, so edits above a pinned site don't unpin it) to
occurrence counts. ``diff_against_baseline`` returns the violations in
excess of the pinned count per fingerprint, plus the stale entries
whose debt has since been paid (surfaced so the baseline shrinks over
time instead of fossilizing).
"""

from __future__ import annotations

import collections
import json

BASELINE_VERSION = 1


def make_baseline(violations) -> dict:
    counts = collections.Counter(v.fingerprint for v in violations)
    return {
        "version": BASELINE_VERSION,
        "note": (
            "Pinned pre-existing tpulint violations. Regenerate with "
            "`python -m ray_tpu._private.lint ray_tpu --update-baseline` "
            "after paying down debt; never regenerate to hide NEW "
            "violations."
        ),
        "entries": {fp: counts[fp] for fp in sorted(counts)},
    }


def load_baseline(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"this tpulint expects {BASELINE_VERSION}"
        )
    return data


def diff_against_baseline(violations, baseline: dict):
    """(new_violations, stale_fingerprints)."""
    allowed = dict(baseline.get("entries", {}))
    by_fp: dict[str, list] = collections.defaultdict(list)
    for v in violations:
        by_fp[v.fingerprint].append(v)
    new = []
    for fp, vs in by_fp.items():
        excess = len(vs) - allowed.get(fp, 0)
        if excess > 0:
            # The later occurrences (by line) are the "new" ones; which
            # physical site is new is unknowable from counts alone, but
            # the report must name real locations.
            vs.sort(key=lambda v: v.line)
            new.extend(vs[-excess:])
    stale = [fp for fp, n in allowed.items()
             if len(by_fp.get(fp, ())) < n]
    new.sort(key=lambda v: (v.path, v.line, v.rule))
    return new, sorted(stale)
