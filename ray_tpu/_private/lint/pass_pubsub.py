"""TPU704 — pubsub channel discipline.

A pubsub channel is a bare string agreed on by publisher and
subscriber; a typo on either side is a subscription that silently
receives nothing, forever. And since PR 16 the head coalesces
publishes per event-loop tick: any channel can deliver a
``{"channel", "batch": [...]}`` frame instead of ``{"channel",
"msg"}``, so a raw ``on_push=`` handler that only unpacks ``msg``
silently drops every message that arrived coalesced — the exact shape
the tqdm_ray/core_worker handlers were fixed to unpack. Two checks:

- channel consistency: every constant channel subscribed to
  (``.call("subscribe", channel="X")`` or ``core.subscribe("X", h)``)
  must have at least one constant publish site (``.publish("X", ...)``
  or ``.call("publish", channel="X", ...)``) in the analyzed program.
  The reverse direction is NOT checked — channels like ``node`` /
  ``actor`` are legitimately subscribed only by tests and dashboards.
- batch-frame safety: a module that subscribes AND installs a raw
  ``on_push=`` handler must unpack batch frames — detected as the
  handler function (resolved module-locally) mentioning the ``batch``
  key anywhere in its body. Subscribers routed through
  ``CoreWorker.subscribe`` are exempt: ``_on_head_push`` unbatches
  centrally before per-channel dispatch.

Dynamic channel strings (variables, f-strings) are out of static
reach and skipped, as is the head's own ``_on_publish`` passthrough.
Reporting is gated on the program containing at least one publish
site (a lone subscriber module has no channel universe to check
against).
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint import protocol
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name, iter_tree


class _State:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.published: set = set()
        self.subscribed: list[tuple] = []   # (channel, line, scope)
        self.subscribes_any = False         # incl. dynamic channels
        self.on_push: list[tuple] = []      # (handler_name, line, scope)
        self.functions: dict[str, ast.AST] = {}


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, st: _State):
        super().__init__(ctx)
        self.st = st

    def enter_function(self, node):
        self.st.functions.setdefault(node.name, node)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        func = node.func
        for kw in node.keywords:
            if kw.arg == "on_push":
                name = dotted_name(kw.value)
                if name:
                    self.st.on_push.append(
                        (name.split(".")[-1], node.lineno, self.scope))
        if not isinstance(func, ast.Attribute):
            return
        if func.attr == "publish" and node.args:
            ch = _const_str(node.args[0])
            if ch:
                self.st.published.add(ch)
        elif func.attr == "subscribe" and node.args:
            ch = _const_str(node.args[0])
            self.st.subscribes_any = True
            if ch:
                self.st.subscribed.append((ch, node.lineno, self.scope))
        elif func.attr == "call" and node.args:
            verb = _const_str(node.args[0])
            if verb not in ("publish", "subscribe"):
                return
            channel = None
            for kw in node.keywords:
                if kw.arg == "channel":
                    channel = _const_str(kw.value)
            if verb == "publish":
                if channel:
                    self.st.published.add(channel)
            else:
                self.st.subscribes_any = True
                if channel:
                    self.st.subscribed.append(
                        (channel, node.lineno, self.scope))


def run(ctx: FileContext):
    if not ("publish" in ctx.source or "subscribe" in ctx.source
            or "on_push" in ctx.source):
        return None
    st = _State(ctx)
    _Visitor(ctx, st).visit(ctx.tree)
    if not (st.published or st.subscribed or st.on_push):
        return None
    return st


def _handles_batch(fn: ast.AST) -> bool:
    for node in iter_tree(fn):
        if isinstance(node, ast.Constant) and node.value == "batch":
            return True
    return False


def finalize(states):
    published: set = set()
    for st in states:
        published |= st.published
    for st in states:
        if published:
            for channel, line, scope in st.subscribed:
                if channel not in published:
                    st.ctx.report(
                        "TPU704", protocol.FakeNode(line),
                        f"subscribed channel {channel!r} is never "
                        "published anywhere in the analyzed program — "
                        "a typo'd channel name receives nothing, "
                        "silently, forever "
                        f"(published channels: {sorted(published)})",
                        scope=scope)
        if not st.subscribes_any:
            continue
        seen: set = set()
        for handler, line, scope in st.on_push:
            if handler in seen:
                continue
            seen.add(handler)
            fn = st.functions.get(handler)
            if fn is None:
                continue  # imported handler: out of module-local reach
            if not _handles_batch(fn):
                st.ctx.report(
                    "TPU704", protocol.FakeNode(fn.lineno),
                    f"push handler {handler!r} never unpacks coalesced "
                    '{"channel", "batch": [...]} frames — the head '
                    "batches publishes per event-loop tick, so this "
                    "subscriber silently drops every message that "
                    "arrives coalesced",
                    scope=scope)
    return []
