"""TPU701 — RPC contract drift.

The control plane's ~83 ``*.call("method", **kw)`` sites and ~40
``async def _on_<method>(self, conn, ...)`` handlers are bound only by
a string at runtime — and ``rpc.tolerant_kwargs`` silently drops any
kwarg the handler doesn't accept (version-skew tolerance), so a typo'd
method name raises late and a typo'd kwarg never raises at all. This
pass binds every string-method call site to the program-wide handler
table (``lint/protocol.py``) and reports:

- unknown method names (no ``_on_<m>`` handler anywhere in the
  analyzed program);
- missing required params (required by EVERY handler of that name);
- unknown kwargs (accepted by NO handler of that name);
- positional payload args (``Connection.call(method, timeout=None,
  **kw)`` makes a second positional arg silently become ``timeout``).

``timeout``/``retry`` are client-transport kwargs, consumed before the
frame is written — always exempt. A call site that splats ``**kw``
can't be checked (the kwargs-dict caveat in the ROADMAP); a dynamic
method name (f-string / variable — the ``col_op:<group>`` extension
idiom) is skipped by default and reported as unresolvable only under
``--strict``, where the runtime contract sanitizer takes over.

Reporting is gated on the program defining at least one handler: a
lone caller module analyzed by itself has no contract to check against
(``--changed`` keeps the gate sound by expanding import neighbors).
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint import protocol
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name

#: Receivers whose ``.call`` is not an RPC (stdlib / test doubles).
_NON_RPC_RECEIVERS = ("subprocess", "mock")


class _CallSite:
    __slots__ = ("ctx", "line", "method", "kwargs", "splat",
                 "extra_pos", "scope", "dynamic")

    def __init__(self, ctx, line, method, kwargs, splat, extra_pos,
                 scope, dynamic):
        self.ctx = ctx
        self.line = line
        self.method = method          # str, or None when dynamic
        self.kwargs = kwargs          # payload kwarg names (transport excluded)
        self.splat = splat            # call had **kw
        self.extra_pos = extra_pos    # positional args beyond the method name
        self.scope = scope
        self.dynamic = dynamic


class _State:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.handlers: list = []
        self.sites: list[_CallSite] = []


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, st: _State):
        super().__init__(ctx)
        self.st = st

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "call"):
            return
        recv = dotted_name(func.value)
        base = recv.split(".")[0] if recv else ""
        if base in _NON_RPC_RECEIVERS or not node.args:
            return
        head = node.args[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            method, dynamic = head.value, False
        elif isinstance(head, (ast.JoinedStr, ast.Name, ast.Attribute)):
            method, dynamic = None, True
        else:
            return  # not a method-name shape (e.g. subprocess argv list)
        kwargs = set()
        splat = False
        for kw in node.keywords:
            if kw.arg is None:
                splat = True
            elif kw.arg not in protocol.TRANSPORT_KWARGS:
                kwargs.add(kw.arg)
        self.st.sites.append(_CallSite(
            self.ctx, node.lineno, method, kwargs, splat,
            len(node.args) - 1, self.scope, dynamic))


def run(ctx: FileContext):
    has_handlers = "_on_" in ctx.source
    has_calls = ".call(" in ctx.source
    if not has_handlers and not has_calls:
        return None
    st = _State(ctx)
    if has_handlers:
        st.handlers = protocol.handler_signatures(ctx.tree, path=ctx.path)
    if has_calls:
        _Visitor(ctx, st).visit(ctx.tree)
    if not st.handlers and not st.sites:
        return None
    return st


def finalize(states):
    merged = protocol.merge_signatures(
        h for st in states for h in st.handlers)
    if not merged:
        return []
    for st in states:
        for site in st.sites:
            node = protocol.FakeNode(site.line)
            if site.dynamic:
                if getattr(site.ctx, "strict", False):
                    site.ctx.report(
                        "TPU701", node,
                        "dynamic RPC method name — contract unresolvable "
                        "statically (the runtime contract sanitizer under "
                        "RAY_TPU_SANITIZE=1 covers this site)",
                        scope=site.scope)
                continue
            if site.extra_pos:
                site.ctx.report(
                    "TPU701", node,
                    f"RPC payload for {site.method!r} passed positionally: "
                    "Connection.call(method, timeout=None, **kw) makes the "
                    "second positional arg the TIMEOUT — payload must be "
                    "keyword args",
                    scope=site.scope)
                # The stray positional is almost certainly the payload:
                # kwarg-level diagnostics would just restate the bug.
                continue
            sig = merged.get(site.method)
            if sig is None:
                site.ctx.report(
                    "TPU701", node,
                    f"RPC method {site.method!r} has no _on_{site.method} "
                    "handler in the analyzed program — the call raises "
                    "'unknown method' at runtime",
                    scope=site.scope)
                continue
            if site.splat:
                continue  # **kw splat: contract unchecked (ROADMAP caveat)
            unknown = site.kwargs - sig.params if not sig.varkw else set()
            for kw in sorted(unknown):
                site.ctx.report(
                    "TPU701", node,
                    f"RPC {site.method!r}: kwarg {kw!r} is not accepted by "
                    "any handler — tolerant_kwargs silently DROPS it at "
                    "the server (handler "
                    f"{sig.cls or '?'}._on_{site.method} accepts "
                    f"{sorted(sig.params) or 'no payload params'})",
                    scope=site.scope)
            missing = sig.required - site.kwargs
            for kw in sorted(missing):
                site.ctx.report(
                    "TPU701", node,
                    f"RPC {site.method!r}: required param {kw!r} is never "
                    "passed — the handler raises TypeError on dispatch",
                    scope=site.scope)
    return []
