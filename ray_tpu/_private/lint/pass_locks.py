"""TPU201/TPU202 — lock discipline.

- TPU201: a blocking call (RPC, ``time.sleep``, subprocess, socket,
  ``.result()``, collective op) issued while a
  ``threading.Lock``/``RLock`` ``with``-block is open. Holding a head
  or node lock across a blocking call is how one slow peer stalls the
  whole control plane (and how PR 3's drain fan-out got delayed).
  (``await`` under a held threading lock is TPU203's — the async-lock
  discipline pass.)
- TPU202: cross-function lock-order cycles. Each file contributes a
  static lock-acquisition graph (lock held → lock acquired, including
  one level of call-graph propagation: ``self.foo()`` / module-level
  ``foo()`` resolved by name); cycles across the analyzed file set are
  reported once per strongly-connected component.

Lock detection is name-based (``self._lock``, ``_env_build_lock``,
``self._pool_lock(name)``): a lock that is not named like one is
invisible here — the runtime sanitizer (``ray_tpu/_private/sanitize.py``)
is the dynamic backstop.
"""

from __future__ import annotations

import ast
import dataclasses

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name
from ray_tpu._private.lint.pass_collective import (
    COLLECTIVE_NAMES,
    _RECEIVER_HINTS,
)

_LOCKISH = ("lock", "mutex")
_RPC_RECEIVERS = ("conn", "client", "head", "node", "rpc", "peer", "stub")
_HTTP_RECEIVERS = ("http", "session", "client")
_SOCK_METHODS = frozenset({"connect", "accept", "recv", "recv_into",
                           "sendall"})
_SUBPROCESS_BLOCKING = frozenset({"run", "call", "check_call",
                                  "check_output", "Popen"})


def _lock_expr_name(expr: ast.AST) -> str | None:
    """Dotted name of a with-item that looks like a lock acquisition,
    else None. Handles `self._lock` and factory calls like
    `self._pool_lock(name)`."""
    target = expr.func if isinstance(expr, ast.Call) else expr
    name = dotted_name(target)
    if not name:
        return None
    last = name.split(".")[-1].lower()
    if any(t in last for t in _LOCKISH):
        return name
    return None


@dataclasses.dataclass
class _Loc:
    path: str
    line: int
    snippet: str
    allowed: bool  # TPU202 pragma present at this line


@dataclasses.dataclass
class LockState:
    """Per-file contribution to the cross-file lock graph."""
    # fn_qual → locks it acquires directly
    direct: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    # fn_qual → called fn_quals (name-resolved within this file)
    calls: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    # (held_lock, acquired_lock) → _Loc  (direct nested acquisition)
    edges: dict[tuple[str, str], _Loc] = dataclasses.field(
        default_factory=dict)
    # (fn_qual_callee, held_lock) → _Loc (call made while holding)
    held_calls: list[tuple[str, str, _Loc]] = dataclasses.field(
        default_factory=list)


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        self.state = LockState()
        self._held: list[str] = []
        # `from a import _table_lock` → _table_lock belongs to module a:
        # references here must unify with a's own, or a cross-FILE
        # inversion could never close its cycle.
        self._imports: dict[str, str] = {}
        for node in ctx.nodes:
            if isinstance(node, ast.ImportFrom) and node.module:
                src = node.module.split(".")[-1]
                for alias in node.names:
                    if alias.name != "*":
                        self._imports[alias.asname or alias.name] = src

    # --------------------------------------------------- naming
    def _qualify(self, name: str) -> str:
        """self.X → Class.X; bare/module-dotted → module.X — so the
        same lock reached from two methods unifies into one node."""
        parts = name.split(".")
        if parts[0] in ("self", "cls") and self._class:
            return f"{self._class[-1]}.{'.'.join(parts[1:])}"
        if parts[0] in self._imports:
            return f"{self._imports[parts[0]]}.{name}"
        return f"{self.ctx.module}.{name}"

    def _fn_qual(self) -> str:
        if self._class and self._func:
            return f"{self._class[-1]}.{self._func[-1]}"
        if self._func:
            return f"{self.ctx.module}.{self._func[-1]}"
        return f"{self.ctx.module}.<module>"

    def _callee_qual(self, call: ast.Call) -> str | None:
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name) and func.value.id in ("self", "cls"):
            if self._class:
                return f"{self._class[-1]}.{func.attr}"
        elif isinstance(func, ast.Name):
            src = self._imports.get(func.id, self.ctx.module)
            return f"{src}.{func.id}"
        return None

    def _loc(self, node: ast.AST) -> _Loc:
        line = getattr(node, "lineno", 1)
        return _Loc(
            path=self.ctx.path,
            line=line,
            snippet=self.ctx.snippet(line),
            allowed=self.ctx.allowed(line, "TPU202"),
        )

    # --------------------------------------------------- blocking calls
    def _blocking_reason(self, call: ast.Call) -> str | None:
        name = dotted_name(call.func)
        if not name:
            return None
        head, _, method = name.rpartition(".")
        head_last = head.split(".")[-1].lower() if head else ""
        if name == "time.sleep" or name == "sleep":
            return "time.sleep"
        if method == "result":
            return f"`{name}()` (future/RPC wait)"
        if method == "call" and any(r in head_last for r in _RPC_RECEIVERS):
            return f"blocking RPC `{name}`"
        if method == "request" and any(
                r in head_last for r in _HTTP_RECEIVERS):
            return f"HTTP request `{name}`"
        if method == "urlopen" or name == "urlopen":
            return f"`{name}` (network I/O)"
        if head_last == "subprocess" and method in _SUBPROCESS_BLOCKING:
            return f"`{name}` (subprocess)"
        if name == "fcntl.flock" or method == "flock":
            return f"`{name}` (file lock)"
        if method in _SOCK_METHODS and "sock" in head_last:
            return f"socket op `{name}`"
        if method in COLLECTIVE_NAMES and (
                any(h in head_last for h in _RECEIVER_HINTS)):
            return f"collective op `{name}`"
        if not head and name in COLLECTIVE_NAMES:
            # Only with collective import context would a bare name be
            # certain; accept the name match here — fixtures and real
            # call sites both read `allreduce(...)`.
            return f"collective op `{name}`"
        return None

    # --------------------------------------------------- visitors
    def _visit_func(self, node):
        # A function DEFINED under a with-block does not run there.
        held, self._held = self._held, []
        super()._visit_func(node)
        self._held = held

    def visit_Lambda(self, node: ast.Lambda):
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    def visit_With(self, node: ast.With):
        fn = self._fn_qual()
        acquired: list[str] = []
        for item in node.items:
            lock_name = _lock_expr_name(item.context_expr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            if lock_name is None:
                continue
            lock_id = self._qualify(lock_name)
            self.state.direct.setdefault(fn, set()).add(lock_id)
            for held in self._held:
                if held != lock_id:
                    self.state.edges.setdefault(
                        (held, lock_id), self._loc(node))
            self._held.append(lock_id)
            acquired.append(lock_id)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    # NOTE: `await` under a held threading lock moved to TPU203
    # (pass_async_locks) — the async-lock discipline pass owns every
    # event-loop/lock interaction now.

    def visit_Call(self, node: ast.Call):
        fn = self._fn_qual()
        callee = self._callee_qual(node)
        if callee is not None:
            self.state.calls.setdefault(fn, set()).add(callee)
            if self._held:
                loc = self._loc(node)
                for held in self._held:
                    self.state.held_calls.append((callee, held, loc))
        if self._held:
            reason = self._blocking_reason(node)
            if reason is not None:
                self.ctx.report(
                    "TPU201", node,
                    f"{reason} while holding `{self._held[-1]}`: move "
                    "the blocking call outside the critical section",
                    scope=self.scope,
                )
        self.generic_visit(node)


def run(ctx: FileContext):
    v = _Visitor(ctx)
    v.visit(ctx.tree)
    return v.state


# ------------------------------------------------------------ finalize
def _acquire_closure(states) -> dict[str, set[str]]:
    direct: dict[str, set[str]] = {}
    calls: dict[str, set[str]] = {}
    for st in states:
        for fn, locks in st.direct.items():
            direct.setdefault(fn, set()).update(locks)
        for fn, cs in st.calls.items():
            calls.setdefault(fn, set()).update(cs)
    closure = {fn: set(locks) for fn, locks in direct.items()}
    # Fixpoint over the (acyclic or not) call graph; bounded by the
    # total number of (fn, lock) pairs so recursion can't spin.
    changed = True
    while changed:
        changed = False
        for fn, cs in calls.items():
            got = closure.setdefault(fn, set())
            before = len(got)
            for c in cs:
                got.update(closure.get(c, ()))
            if len(got) != before:
                changed = True
    return closure


def _sccs(graph: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan, iterative."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in graph:
                    continue
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def finalize(states):
    from ray_tpu._private.lint.core import RULES, Violation

    closure = _acquire_closure(states)
    edges: dict[tuple[str, str], _Loc] = {}
    for st in states:
        for key, loc in st.edges.items():
            edges.setdefault(key, loc)
        for callee, held, loc in st.held_calls:
            for lock in closure.get(callee, ()):
                if lock != held:
                    edges.setdefault((held, lock), loc)

    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    violations = []
    for comp in _sccs(graph):
        comp_set = set(comp)
        comp_edges = sorted(
            (k for k in edges
             if k[0] in comp_set and k[1] in comp_set),
            key=lambda k: (edges[k].path, edges[k].line),
        )
        anchor = next(
            (k for k in comp_edges if not edges[k].allowed), None)
        if anchor is None:
            continue  # every contributing edge is pragma'd
        loc = edges[anchor]
        cycle = " -> ".join(comp + [comp[0]])
        violations.append(Violation(
            rule="TPU202",
            name=RULES["TPU202"],
            path=loc.path,
            line=loc.line,
            col=0,
            message=(
                f"lock-order cycle {cycle}: two threads taking these "
                "locks in opposite orders deadlock; pick one global "
                "order"
            ),
            scope="|".join(comp),
            snippet=loc.snippet,
        ))
    return violations
