"""Shared jit-boundary detection for the TPU60x rule family.

TPU602/603/604/605 all need the same three facts about a module:

- which FUNCTIONS are traced by XLA (``@jax.jit`` / ``@partial(jax.jit,
  ...)`` decorated defs, plus functions passed BY REFERENCE into a
  ``jit(...)`` call — their bodies run exactly once, at trace time),
- which NAMES are bound to compiled callables (``self._prefill =
  jax.jit(..., donate_argnums=(2,))`` — the call through the name is a
  compiled-program invocation, and the donate/static metadata travels
  with it),
- which functions are jit FACTORIES (their ``return`` is a ``jit(...)``
  call — ``jit_train_step()`` hands its caller a donated compiled step,
  so ``step = jit_train_step(...)`` makes ``step`` a donated callable
  in another file entirely).

Collected once per module and cached on the FileContext so the four
passes share one walk, exactly like ``dataflow.index``.
"""

from __future__ import annotations

import ast
import dataclasses

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, dotted_name, iter_tree, iter_children

#: Names that create a compiled callable when called.
JIT_NAMES = frozenset({"jit", "pjit"})


@dataclasses.dataclass
class JitInfo:
    """Metadata for one jit(...) creation site."""

    line: int
    #: positional indexes named in donate_argnums, () when absent and
    #: None when present but not statically evaluable (conditional
    #: tuples etc. — unknown must never report).
    donate: tuple | None = ()
    #: positional indexes named in static_argnums (same None semantics).
    static: tuple | None = ()
    #: qualname of the wrapped function when jit() received a
    #: resolvable reference (jit(step) / jit(partial(step, ...))).
    wrapped: str | None = None


def _int_tuple(node: ast.AST) -> tuple | None:
    """Statically evaluate an int / tuple-of-ints argnums expression;
    None when it cannot be evaluated (conditional, computed)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def jit_call_info(call: ast.Call,
                  mi: dataflow.ModuleIndex | None = None,
                  class_name: str | None = None) -> JitInfo | None:
    """JitInfo when ``call`` is a jit/pjit invocation, else None.

    ``partial(jax.jit, static_argnums=...)`` (the decorator-factory
    idiom) is treated as the jit call itself — its keywords ARE the jit
    keywords.
    """
    fname = dotted_name(call.func)
    tail = fname.split(".")[-1] if fname else ""
    inner = None
    if tail == "partial" and call.args:
        first = dotted_name(call.args[0])
        if first and first.split(".")[-1] in JIT_NAMES:
            inner = call
            tail = first.split(".")[-1]
    if tail not in JIT_NAMES:
        return None

    info = JitInfo(line=call.lineno)
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            info.donate = _int_tuple(kw.value)
        elif kw.arg == "static_argnums":
            info.static = _int_tuple(kw.value)

    # The wrapped function: jit(step) / jit(partial(step, cfg=...)).
    args = call.args if inner is None else call.args[1:]
    if args:
        target = args[0]
        if isinstance(target, ast.Call):
            tf = dotted_name(target.func)
            if tf and tf.split(".")[-1] == "partial" and target.args:
                target = target.args[0]
        tname = dotted_name(target)
        if tname and mi is not None:
            # Resolve through the module's import map so a wrapped
            # foreign function unifies with its definition.
            info.wrapped = mi.qualify(tname, class_name)
    return info


def _is_jit_decorator(dec: ast.AST) -> JitInfo | None:
    """JitInfo for @jax.jit / @jit / @partial(jax.jit, ...) /
    @jax.jit(...) decorator nodes."""
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        tail = fname.split(".")[-1] if fname else ""
        if tail in JIT_NAMES:
            info = JitInfo(line=dec.lineno)
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    info.donate = _int_tuple(kw.value)
                elif kw.arg == "static_argnums":
                    info.static = _int_tuple(kw.value)
            return info
        if tail == "partial" and dec.args:
            first = dotted_name(dec.args[0])
            if first and first.split(".")[-1] in JIT_NAMES:
                info = JitInfo(line=dec.lineno)
                for kw in dec.keywords:
                    if kw.arg == "donate_argnums":
                        info.donate = _int_tuple(kw.value)
                    elif kw.arg == "static_argnums":
                        info.static = _int_tuple(kw.value)
                return info
        return None
    name = dotted_name(dec)
    if name and name.split(".")[-1] in JIT_NAMES:
        return JitInfo(line=getattr(dec, "lineno", 1))
    return None


class ModuleJitIndex:
    """Per-module jit facts, cached on the FileContext."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.mi = dataflow.index(ctx)
        #: fn qual -> JitInfo for jit-DECORATED defs
        self.jit_defs: dict[str, JitInfo] = {}
        #: canonical var/attr name -> JitInfo for `v = jit(...)` binds
        self.jit_vars: dict[str, JitInfo] = {}
        #: fn qual -> JitInfo for functions RETURNING a jit(...) call
        self.factories: dict[str, JitInfo] = {}
        #: quals of functions passed by reference into a jit() call
        #: (their bodies are traced)
        self.wrapped: set[str] = set()
        #: canonical var name -> callee qual, for `v = some_factory()`
        #: binds whose factory-ness is only known program-wide
        self.maybe_factory_vars: dict[str, str] = {}
        # No textual prefilter: a CALLER of a jit factory has no "jit"
        # token anywhere — factory-var binds must be collected in every
        # file or the cross-file TPU604/605 events never form.
        self._collect()

    # ----------------------------------------------------------- collect
    def _collect(self) -> None:
        has_jit = "jit" in self.ctx.source
        for qual, info in self.mi.functions.items():
            if not has_jit:
                break
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                ji = _is_jit_decorator(dec)
                if ji is not None:
                    self.jit_defs[qual] = ji
            for child in iter_tree(node):
                if isinstance(child, ast.Return) and isinstance(
                        child.value, ast.Call):
                    ji = jit_call_info(child.value, self.mi,
                                       info.class_name)
                    if ji is not None:
                        # First donated return wins (multiple returns
                        # share the factory's contract in practice).
                        if qual not in self.factories or ji.donate:
                            self.factories[qual] = ji
                        if ji.wrapped:
                            self.wrapped.add(ji.wrapped)

        def walk_assigns(node, class_name):
            for child in iter_children(node):
                if isinstance(child, ast.ClassDef):
                    walk_assigns(child, child.name)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk_assigns(child, class_name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call):
                    ji = jit_call_info(child.value, self.mi, class_name)
                    for target in child.targets:
                        tname = dotted_name(target)
                        if not tname:
                            continue
                        canon = self.mi.qualify(tname, class_name)
                        if ji is not None:
                            self.jit_vars[canon] = ji
                            if ji.wrapped:
                                self.wrapped.add(ji.wrapped)
                        else:
                            callee = self.mi.resolve_call(
                                child.value, class_name)
                            if callee is not None:
                                self.maybe_factory_vars[canon] = callee
                walk_assigns(child, class_name)

        walk_assigns(self.ctx.tree, None)

    # ------------------------------------------------------------ lookup
    def lookup_callable(self, call: ast.Call,
                        class_name: str | None) -> JitInfo | None:
        """JitInfo when ``call`` invokes a module-local jit-bound name
        (``self._prefill(...)`` / ``step(...)``)."""
        name = dotted_name(call.func)
        if not name:
            return None
        canon = self.mi.qualify(name, class_name)
        return self.jit_vars.get(canon)


def jit_index(ctx: FileContext) -> ModuleJitIndex:
    cached = getattr(ctx, "_jit_index", None)
    if cached is None:
        cached = ModuleJitIndex(ctx)
        ctx._jit_index = cached
    return cached
