"""TPU103 — flow-sensitive, interprocedural rank divergence.

TPU101/102 see a collective call only when the verb is syntactically at
the guarded site. The moment the collective moves into a helper::

    def _sync(self):
        col.allreduce(self.grads)      # innocent on its own

    def step(self):
        if self.rank == 0:
            self._sync()               # SPMD hang — invisible to TPU101

the old pass goes blind. TPU103 closes that hole: the dataflow engine's
call graph computes the set of functions that *transitively* issue a
collective op, and a flow-sensitive walk flags any call into that set
made (a) under a rank-/``slice_label``-dependent branch or (b) on a
path that survives a rank-dependent early exit. Direct collective calls
stay TPU101/102 (one site, one rule)."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, iter_tree
from ray_tpu._private.lint.pass_collective import (
    COLLECTIVE_NAMES,
    _RANK_TOKENS,
    _RECEIVER_HINTS,
    is_rank_dependent,
)
from ray_tpu._private.lint.core import dotted_name

# slice_label is the PR-8 fault-domain twin of rank: a collective
# guarded by "which slice am I on" diverges exactly like a rank guard.
_FLOW_TOKENS = tuple(_RANK_TOKENS) + ("slice_label", "slice_index")


def _is_divergence_test(test: ast.AST) -> bool:
    if is_rank_dependent(test):
        return True
    for node in iter_tree(test):
        name = ""
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(t in name.lower() for t in _FLOW_TOKENS):
            return True
    return False


def _is_direct_collective(call: ast.Call, imported: set[str],
                          aliases: set[str]) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in imported
    if isinstance(func, ast.Attribute) and func.attr in COLLECTIVE_NAMES:
        recv = dotted_name(func.value)
        last = recv.split(".")[-1].lower() if recv else ""
        if recv and recv.split(".")[0] in aliases:
            return True
        return any(h in last for h in _RECEIVER_HINTS)
    return False


class _State(dataflow.PathState):
    __slots__ = ("guards", "early_exit")

    def __init__(self):
        self.guards: tuple = ()          # lines of active rank guards
        self.early_exit: tuple | None = None   # (line, kind)

    def fork(self):
        st = _State()
        st.guards = self.guards
        st.early_exit = self.early_exit
        return st

    def merge(self, other):
        # Joining with a path that carries an early-exit taint keeps
        # the taint: SOME ranks may have left before this point.
        if self.early_exit is None:
            self.early_exit = other.early_exit


class _Walker(dataflow.FlowWalker):
    def __init__(self, pass_state, mi: dataflow.ModuleIndex,
                 info: dataflow.FunctionInfo,
                 imported: set[str], aliases: set[str]):
        self.pass_state = pass_state
        self.mi = mi
        self.info = info
        self._imported = imported
        self._aliases = aliases

    def on_branch(self, test, state, taken):
        if _is_divergence_test(test):
            state.guards = state.guards + (test.lineno,)
            return True
        return None

    def on_branch_exit(self, token, state):
        if token and state.guards:
            state.guards = state.guards[:-1]

    def on_if_join(self, stmt, state, then_exited, else_exited):
        if state is None or not _is_divergence_test(stmt.test):
            return
        if then_exited or else_exited:
            arm = stmt.body if then_exited else stmt.orelse
            kind = type(arm[-1]).__name__.lower() if arm else "return"
            state.early_exit = (stmt.lineno, kind)

    def on_call(self, call, state):
        if not state.guards and state.early_exit is None:
            return
        if _is_direct_collective(call, self._imported, self._aliases):
            return  # TPU101/102 own the direct-call shape
        callee = self.mi.resolve_call(call, self.info.class_name)
        if callee is None:
            return
        self.pass_state.events.append((
            self.info.ctx, callee, call.lineno,
            tuple(state.guards), state.early_exit, self.info.qual,
            self._scope(),
        ))

    def _scope(self):
        if self.info.class_name:
            return f"{self.info.class_name}.{self.info.node.name}"
        return self.info.node.name


class _PassState:
    def __init__(self, mi: dataflow.ModuleIndex):
        self.mi = mi
        # (ctx, callee, line, guard_lines, early_exit, caller, scope)
        self.events: list[tuple] = []
        # fn quals in this module that DIRECTLY call a collective verb
        self.direct: set[str] = set()


def _collective_import_context(nodes):
    aliases: set[str] = set()
    names: set[str] = set()
    for node in nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "collective":
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == "collective":
                for a in node.names:
                    if a.name in COLLECTIVE_NAMES:
                        names.add(a.asname or a.name)
    return aliases, names


def run(ctx: FileContext):
    # Cheap textual pre-filter: no collective verb token anywhere means
    # no function here can be (or call) an issuer this module observes.
    src = ctx.source
    if not any(v in src for v in ("allreduce", "allgather", "barrier",
                                  "reducescatter", "broadcast",
                                  "sendrecv")):
        interesting = False
    else:
        interesting = True
    mi = dataflow.index(ctx)
    st = _PassState(mi)
    aliases, imported = _collective_import_context(ctx.nodes)
    for qual, info in mi.functions.items():
        if interesting:
            for node in iter_tree(info.node):
                if isinstance(node, ast.Call) and _is_direct_collective(
                        node, imported, aliases):
                    st.direct.add(qual)
                    break
    # Flow walk every function that contains a divergence token — the
    # events only matter if a guard is live.
    lowered = src.lower()
    if any(t in lowered for t in _FLOW_TOKENS):
        for info in mi.functions.values():
            walker = _Walker(st, mi, info, imported, aliases)
            walker.walk_function(info.node, _State())
    return st


def finalize(states):
    program = dataflow.Program([st.mi for st in states])
    direct: set[str] = set()
    for st in states:
        direct.update(st.direct)
    if not direct:
        return []
    issuers = program.closure(direct)
    seen: set[tuple] = set()  # loop bodies are walked twice — dedupe
    for st in states:
        for (ctx, callee, line, guards, early_exit, caller,
             scope) in st.events:
            if callee not in issuers:
                continue
            if callee not in program.functions:
                continue  # unresolved foreign name that happens to match
            key = (id(ctx), line, callee)
            if key in seen:
                continue
            seen.add(key)
            if guards:
                ctx.report(
                    "TPU103", _FakeNode(line),
                    f"`{callee}()` transitively issues a collective op "
                    f"but is called under a rank-/slice-dependent "
                    f"branch (guard at line {guards[-1]}): ranks that "
                    "skip this path never join the rendezvous (SPMD "
                    "hang hidden behind a helper)",
                    scope=scope,
                )
            elif early_exit is not None:
                ex_line, kind = early_exit
                ctx.report(
                    "TPU103", _FakeNode(line),
                    f"`{callee}()` transitively issues a collective op "
                    f"after the rank-dependent early `{kind}` on line "
                    f"{ex_line}: exited ranks never reach the "
                    "rendezvous inside the helper",
                    scope=scope,
                )
    return []


class _FakeNode:
    """Line-only node stand-in for ctx.report (events outlive their
    ast nodes cheaply this way)."""

    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno: int, col: int = 0):
        self.lineno = lineno
        self.col_offset = col
