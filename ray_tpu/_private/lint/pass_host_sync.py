"""TPU601 — host↔device sync in a hot path.

A ``block_until_ready`` / ``jax.device_get`` / ``.item()`` / ``float(arr)``
inside the step loop stalls the dispatch pipeline: the host stops feeding
XLA, the device drains, and the step time grows by the full round-trip —
the exact bug class behind the ROADMAP's "jitted step serializes comm the
eager path overlaps" plateau. The pass is REGION-based:

- a **compute-phase span body** (``with sp.phase("compute"):``) is the
  hottest region: every host-sync form fires there, including the weak
  ones (``float(x)`` / ``int(x)`` / ``np.asarray(x)``) that force an
  implicit transfer.
- a **step region** — the body of a ``with train.step_span():`` block or
  of a loop that drives step spans / ``report()`` (the codebase's two
  step-loop markers) — fires only on the explicit sync verbs
  (``block_until_ready`` / ``device_get`` / ``.item()``): a ``float()``
  on an already-host value is routine bookkeeping there.
- a **non-compute phase body** (``phase("collective")`` /
  ``phase("data_wait")`` / ``phase("checkpoint")``) is *shielded*:
  blocking is the declared semantics of those phases (that is where the
  PR-10 tail join lives).

Reach is transitive: a call from a hot region into a helper that
(anywhere down the call graph) issues an explicit sync verb is flagged
at the call site — the engine's reverse closure, same as TPU103.
``wait()`` / ``wait_pending()`` calls are exempt everywhere: joining an
async CollectiveWork handle is the DESIGNED sync point of the overlap
machinery, not an accident."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name, iter_tree

#: Explicit sync verbs (fire in any hot region, and seed the closure).
STRONG_SYNCS = frozenset({"block_until_ready", "device_get"})
#: The designed join points of the overlap machinery — never a finding,
#: and never followed into the closure.
WAIT_EXEMPT = frozenset({"wait", "wait_pending", "wait_all", "join"})
#: Callee tails that are end-of-step bookkeeping by design: the step
#: accounting itself may sample/sync, and flagging it would indict the
#: telemetry for existing.
_BOOKKEEPING_TAILS = frozenset({
    "report", "step_span", "finish_step", "implicit_step", "step_sample",
    "flush_observability",
})
_HOT_MARKERS = ("step_span", ".phase(", "report(")


def _sync_kind(call: ast.Call) -> str | None:
    """'block_until_ready'/'device_get'/'.item()' for strong syncs,
    'float()'/'int()'/'np.asarray()' for weak ones, else None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in STRONG_SYNCS:
            return func.attr
        if func.attr == "item" and not call.args and not call.keywords:
            return ".item()"
        if func.attr in ("asarray", "array"):
            recv = dotted_name(func.value)
            if recv.split(".")[-1] in ("np", "numpy"):
                return f"np.{func.attr}()"
        return None
    if isinstance(func, ast.Name):
        if func.id in STRONG_SYNCS:
            return func.id
        if func.id in ("float", "int") and len(call.args) == 1 \
                and not call.keywords and not isinstance(
                    call.args[0], ast.Constant):
            return f"{func.id}()"
    return None


def _is_weak(kind: str) -> bool:
    return kind in ("float()", "int()", "np.asarray()", "np.array()")


def _is_step_loop(node: ast.AST) -> bool:
    """A loop that drives the train-step machinery: its body contains a
    ``step_span``/``phase`` span entry or a ``report()`` call."""
    for child in iter_tree(node):
        if not isinstance(child, ast.Call):
            continue
        func = child.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name in ("step_span", "report"):
            return True
    return False


def _phase_name(call: ast.Call) -> str | None:
    """'compute' / 'collective' / … for a ``*.phase("x")`` call."""
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else "")
    if name != "phase" or not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return "?"


def _is_step_span_entry(call: ast.Call) -> bool:
    func = call.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else "")
    return name == "step_span"


# Region lattice: NONE < STEP < COMPUTE; SHIELDED masks everything.
_NONE, _STEP, _COMPUTE, _SHIELDED = 0, 1, 2, 3


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, mi: dataflow.ModuleIndex,
                 st: "_PassState"):
        super().__init__(ctx)
        self.mi = mi
        self.st = st
        self._region: list[int] = [_NONE]

    # ------------------------------------------------------------ regions
    @property
    def region(self) -> int:
        return self._region[-1]

    def enter_function(self, node):
        # A nested def's body does not execute in the enclosing
        # region — it runs whenever it is called.
        self._region.append(_NONE)

    def exit_function(self, node):
        self._region.pop()

    def _with_region(self, region: int, body_visit) -> None:
        self._region.append(region)
        body_visit()
        self._region.pop()

    def _visit_with(self, node):
        region = None
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                ph = _phase_name(expr)
                if ph == "compute":
                    region = _COMPUTE
                elif ph is not None:
                    # Declared non-compute phase: blocking is its
                    # semantics (data_wait/collective/checkpoint).
                    region = _SHIELDED
                elif _is_step_span_entry(expr) and region is None:
                    region = _STEP
            self.visit(expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        if region is None:
            region = self.region

        def body():
            for stmt in node.body:
                self.visit(stmt)

        self._with_region(region, body)

    def visit_With(self, node):
        self._visit_with(node)

    def visit_AsyncWith(self, node):
        self._visit_with(node)

    def _visit_loop(self, node):
        self.visit(node.iter) if isinstance(
            node, (ast.For, ast.AsyncFor)) else self.visit(node.test)
        region = self.region
        if region == _NONE and _is_step_loop(node):
            region = _STEP

        def body():
            for stmt in node.body:
                self.visit(stmt)
            for stmt in node.orelse:
                self.visit(stmt)

        self._with_region(region, body)

    def visit_For(self, node):
        self._visit_loop(node)

    def visit_AsyncFor(self, node):
        self._visit_loop(node)

    def visit_While(self, node):
        self._visit_loop(node)

    # ------------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call):
        region = self.region
        if region in (_NONE, _SHIELDED):
            self.generic_visit(node)
            return
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else ""
        if attr in WAIT_EXEMPT:
            self.generic_visit(node)
            return
        kind = _sync_kind(node)
        if kind is not None:
            if _is_weak(kind) and region != _COMPUTE:
                self.generic_visit(node)
                return
            where = ("inside a compute-phase span"
                     if region == _COMPUTE else "inside the step loop")
            self.ctx.report(
                "TPU601", node,
                f"host sync `{kind}` {where}: the host blocks on the "
                "device and the dispatch pipeline drains — every "
                "in-flight program behind it serializes. Move it out "
                "of the hot path, batch it per-N-steps, or annotate "
                "the blocking phase it belongs to",
                scope=self.scope,
            )
        else:
            callee = self.mi.resolve_call(
                node, self._class[-1] if self._class else None)
            if callee is not None and callee.split(
                    ".")[-1] not in _BOOKKEEPING_TAILS | WAIT_EXEMPT:
                self.st.events.append((
                    self.ctx, callee, node.lineno, region, self.scope))
        self.generic_visit(node)


class _PassState:
    def __init__(self, mi: dataflow.ModuleIndex):
        self.mi = mi
        # (ctx, callee, line, region, scope) — hot calls to resolve
        self.events: list[tuple] = []
        # fn qual -> sync kind for functions with a DIRECT strong sync
        self.direct: dict[str, str] = {}


def run(ctx: FileContext):
    src = ctx.source
    mi = dataflow.index(ctx)
    st = _PassState(mi)
    # Seed collection runs everywhere: a helper file with no hot region
    # of its own still taints its callers.
    if "block_until_ready" in src or "device_get" in src \
            or ".item()" in src:
        for qual, info in mi.functions.items():
            if qual.split(".")[-1] in WAIT_EXEMPT:
                continue  # the designed join points never taint callers
            for node in iter_tree(info.node):
                if isinstance(node, ast.Call):
                    kind = _sync_kind(node)
                    if kind is not None and not _is_weak(kind):
                        st.direct[qual] = kind
                        break
    if any(m in src for m in _HOT_MARKERS):
        _Visitor(ctx, mi, st).visit(ctx.tree)
    return st


def finalize(states):
    program = dataflow.Program([st.mi for st in states])
    direct: dict[str, str] = {}
    for st in states:
        direct.update(st.direct)
    if not direct:
        return []
    issuers = program.closure(set(direct))
    seen: set[tuple] = set()
    for st in states:
        for ctx, callee, line, region, scope in st.events:
            if callee not in issuers or callee not in program.functions:
                continue
            key = (id(ctx), line, callee)
            if key in seen:
                continue
            seen.add(key)
            where = ("a compute-phase span" if region == _COMPUTE
                     else "the step loop")
            ctx.report(
                "TPU601", _FakeNode(line),
                f"`{callee}()` transitively reaches an explicit host "
                f"sync (block_until_ready/device_get/.item()) and is "
                f"called inside {where}: the helper stalls the "
                "dispatch pipeline from a hot region — hoist the sync "
                "out or make the helper take the async-handle path",
                scope=scope,
            )
    return []


class _FakeNode:
    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno: int, col: int = 0):
        self.lineno = lineno
        self.col_offset = col
