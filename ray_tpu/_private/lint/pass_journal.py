"""TPU702 — journal replay completeness.

The head's durability contract is three hand-maintained mirrors:
every ``self._journal_append(table, op, payload)`` site must have (a) a
replay branch in ``_restore_from_journal`` matching that ``(table,
op)`` pair, (b) payload keys that cover every ``payload["k"]`` read
the replay performs, and (c) the replayed state captured by
``_snapshot()`` — otherwise compaction silently drops the table. A
drift in any mirror is invisible until a head restart replays (or
fails to replay) the record: the worst kind of bug, destructive and
only reachable through crash-recovery chaos tests.

Model extracted per module, bound program-wide at finalize (append
sites and the restore function may live in different files):

- append sites: ``*._journal_append("table", "op", {...})`` with
  constant table/op; dict-literal payloads contribute their key set,
  anything else (a variable, ``**`` expansion) opts the site out of
  the key check only.
- replay branches: ``table == "T"`` / ``op == "O"`` comparison chains
  inside any ``_restore_from_journal``, including one-hop delegation
  (``self._ckpt_replay(op, payload)``) and ``fn(**payload)`` splats,
  whose required-parameter sets become required payload keys.
- required keys are plain ``payload["k"]`` subscripts; ``payload.get``
  reads are migration-tolerant by design and not required.
- ``_snapshot()``: the set of ``self.X`` attributes it captures.

No restore function in the analyzed program → no reporting (a lone
module of append sites has no replay contract to check against).
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint import protocol
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name, iter_tree

_MUTATORS = frozenset({
    "pop", "update", "setdefault", "difference_update", "add",
    "append", "clear", "discard", "remove",
})


class _Region:
    __slots__ = ("required", "mutated", "delegates", "splats")

    def __init__(self):
        self.required: set = set()   # payload["k"] subscript reads
        self.mutated: set = set()    # self.X attrs written/mutated
        self.delegates: set = set()  # fn names called as fn(op, payload)
        self.splats: set = set()     # fn names called as fn(**payload)

    def merge(self, other: "_Region"):
        self.required |= other.required
        self.mutated |= other.mutated
        self.delegates |= other.delegates
        self.splats |= other.splats


class _Branch:
    """Replay coverage for one journaled table."""

    __slots__ = ("ops", "catchall", "common")

    def __init__(self):
        self.ops: dict[str, _Region] = {}
        self.catchall: _Region | None = None
        self.common = _Region()


def _test_consts(test: ast.AST) -> tuple[list[str], list[str]]:
    """Constants compared against ``table`` / ``op`` anywhere in a
    branch test (BoolOp conjuncts included)."""
    tables, ops = [], []
    for node in iter_tree(test):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], ast.Eq)):
            continue
        left, right = node.left, node.comparators[0]
        if isinstance(left, ast.Constant):
            left, right = right, left
        if not (isinstance(left, ast.Name)
                and isinstance(right, ast.Constant)
                and isinstance(right.value, str)):
            continue
        if left.id == "table":
            tables.append(right.value)
        elif left.id == "op":
            ops.append(right.value)
    return tables, ops


def _collect_region(stmts, region: _Region) -> None:
    for s in stmts:
        for node in iter_tree(s):
            if isinstance(node, ast.Subscript):
                if (isinstance(node.value, ast.Name)
                        and node.value.id == "payload"
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.ctx, ast.Load)):
                    region.required.add(node.slice.value)
                tgt = dotted_name(node.value)
                if tgt.startswith("self.") and isinstance(
                        node.ctx, (ast.Store, ast.Del)):
                    region.mutated.add(tgt.split(".")[1])
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    name = dotted_name(t)
                    if name.startswith("self."):
                        region.mutated.add(name.split(".")[1])
            elif isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute):
                    recv = dotted_name(func.value)
                    if func.attr in _MUTATORS and recv.startswith("self."):
                        region.mutated.add(recv.split(".")[1])
                    arg_names = {a.id for a in node.args
                                 if isinstance(a, ast.Name)}
                    if {"op", "payload"} <= arg_names:
                        region.delegates.add(func.attr)
                    for kw in node.keywords:
                        if (kw.arg is None and isinstance(kw.value, ast.Name)
                                and kw.value.id == "payload"):
                            region.splats.add(func.attr)


def _visit_branches(stmts, table: str | None, op: str | None,
                    model: dict[str, _Branch]) -> None:
    for s in stmts:
        if isinstance(s, ast.If):
            tnames, onames = _test_consts(s.test)
            if table is None and tnames:
                for t in tnames:
                    model.setdefault(t, _Branch())
                    if onames:
                        for o in onames:
                            _visit_branches(
                                s.body, t, o, model)
                    else:
                        _visit_branches(s.body, t, None, model)
                _visit_branches(s.orelse, None, None, model)
                continue
            if table is not None and op is None and onames:
                branch = model.setdefault(table, _Branch())
                for o in onames:
                    region = branch.ops.setdefault(o, _Region())
                    _collect_region(s.body, region)
                if s.orelse:
                    if (len(s.orelse) == 1
                            and isinstance(s.orelse[0], ast.If)):
                        _visit_branches(s.orelse, table, None, model)
                    else:
                        if branch.catchall is None:
                            branch.catchall = _Region()
                        _collect_region(s.orelse, branch.catchall)
                continue
        if table is not None:
            branch = model.setdefault(table, _Branch())
            if op is None:
                _collect_region([s], branch.common)
            else:
                _collect_region([s], branch.ops.setdefault(op, _Region()))
        elif isinstance(s, (ast.For, ast.AsyncFor, ast.While, ast.With,
                            ast.AsyncWith, ast.Try)):
            for body in (getattr(s, "body", []), getattr(s, "orelse", []),
                         getattr(s, "finalbody", [])):
                _visit_branches(body, None, None, model)
            for h in getattr(s, "handlers", []):
                _visit_branches(h.body, None, None, model)


class _AppendSite:
    __slots__ = ("ctx", "line", "table", "op", "keys", "scope")

    def __init__(self, ctx, line, table, op, keys, scope):
        self.ctx = ctx
        self.line = line
        self.table = table
        self.op = op
        self.keys = keys  # set of const payload keys, or None (unchecked)
        self.scope = scope


class _State:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.appends: list[_AppendSite] = []
        self.restore_model: dict[str, _Branch] = {}
        self.has_restore = False
        self.snapshot_attrs: set = set()
        self.has_snapshot = False
        self.functions: dict[str, ast.AST] = {}


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, st: _State):
        super().__init__(ctx)
        self.st = st

    def enter_function(self, node):
        self.st.functions.setdefault(node.name, node)
        if node.name == "_restore_from_journal":
            self.st.has_restore = True
            _visit_branches(node.body, None, None, self.st.restore_model)
        elif node.name == "_snapshot":
            self.st.has_snapshot = True
            for sub in iter_tree(node):
                name = dotted_name(sub)
                if name.startswith("self.") and name.count(".") == 1:
                    self.st.snapshot_attrs.add(name.split(".")[1])

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr == "_journal_append") or len(node.args) < 3:
            return
        table_n, op_n, payload = node.args[0], node.args[1], node.args[2]
        if not (isinstance(table_n, ast.Constant)
                and isinstance(op_n, ast.Constant)):
            return  # dynamic table/op: out of static reach
        keys = None
        if isinstance(payload, ast.Dict) and all(
                isinstance(k, ast.Constant) for k in payload.keys):
            keys = {k.value for k in payload.keys}
        self.st.appends.append(_AppendSite(
            self.ctx, node.lineno, table_n.value, op_n.value, keys,
            self.scope))


def run(ctx: FileContext):
    if "_journal_append" not in ctx.source and (
            "_restore_from_journal" not in ctx.source):
        return None
    st = _State(ctx)
    _Visitor(ctx, st).visit(ctx.tree)
    if not st.appends and not st.has_restore:
        return None
    return st


def _required_params(fn: ast.AST) -> set:
    args = fn.args
    pos = [a.arg for a in args.args]
    n_def = len(args.defaults)
    req = set(pos[: len(pos) - n_def]) if n_def else set(pos)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is None:
            req.add(a.arg)
    req.discard("self")
    return req


def _resolve_delegates(model: dict[str, _Branch],
                       functions: dict[str, ast.AST]) -> None:
    """Fold one-hop delegation (``self._ckpt_replay(op, payload)``)
    into the delegating table's branch: the delegate's own op-dispatch
    becomes the table's op coverage, and ``fn(**payload)`` splats
    inside it contribute the callee's required params as required
    payload keys."""
    for branch in model.values():
        for region in [branch.common, branch.catchall,
                       *branch.ops.values()]:
            if region is None:
                continue
            for name in sorted(region.splats):
                fn = functions.get(name)
                if fn is not None:
                    region.required |= _required_params(fn)
            for name in sorted(region.delegates):
                fn = functions.get(name)
                if fn is None:
                    continue
                sub: dict[str, _Branch] = {}
                _visit_branches(fn.body, "<delegate>", None, sub)
                deleg = sub.get("<delegate>")
                if deleg is None:
                    continue
                for op, op_region in deleg.ops.items():
                    for sname in sorted(op_region.splats):
                        sfn = functions.get(sname)
                        if sfn is not None:
                            op_region.required |= _required_params(sfn)
                    branch.ops.setdefault(op, _Region()).merge(op_region)
                branch.common.merge(deleg.common)
                if deleg.catchall is not None:
                    if branch.catchall is None:
                        branch.catchall = _Region()
                    branch.catchall.merge(deleg.catchall)


def finalize(states):
    model: dict[str, _Branch] = {}
    functions: dict[str, ast.AST] = {}
    snapshot_attrs: set = set()
    has_restore = has_snapshot = False
    for st in states:
        functions.update(st.functions)
        snapshot_attrs |= st.snapshot_attrs
        has_snapshot = has_snapshot or st.has_snapshot
        if st.has_restore:
            has_restore = True
            for t, b in st.restore_model.items():
                if t in model:
                    cur = model[t]
                    cur.common.merge(b.common)
                    for o, r in b.ops.items():
                        cur.ops.setdefault(o, _Region()).merge(r)
                    if b.catchall is not None:
                        if cur.catchall is None:
                            cur.catchall = _Region()
                        cur.catchall.merge(b.catchall)
                else:
                    model[t] = b
    if not has_restore:
        return []
    _resolve_delegates(model, functions)

    snapshot_flagged: set = set()
    for st in states:
        for site in st.appends:
            node = protocol.FakeNode(site.line)
            branch = model.get(site.table)
            if branch is None:
                site.ctx.report(
                    "TPU702", node,
                    f"journal table {site.table!r} has no replay branch "
                    "in _restore_from_journal — records are appended but "
                    "silently dropped on head restart",
                    scope=site.scope)
                continue
            covered = site.op in branch.ops or branch.catchall is not None
            if not covered:
                site.ctx.report(
                    "TPU702", node,
                    f"journal op ({site.table!r}, {site.op!r}) has no "
                    "replay branch (and the table dispatch has no "
                    "catch-all) — the record is skipped on restart",
                    scope=site.scope)
            elif site.keys is not None:
                required = set(branch.common.required)
                if site.op in branch.ops:
                    required |= branch.ops[site.op].required
                elif branch.catchall is not None:
                    required |= branch.catchall.required
                missing = sorted(required - site.keys)
                if missing:
                    site.ctx.report(
                        "TPU702", node,
                        f"journal payload for ({site.table!r}, "
                        f"{site.op!r}) omits key(s) {missing} that the "
                        "replay path reads — restart raises KeyError "
                        "mid-replay",
                        scope=site.scope)
            if has_snapshot and site.table not in snapshot_flagged:
                snapshot_flagged.add(site.table)
                mutated = set(branch.common.mutated)
                for r in branch.ops.values():
                    mutated |= r.mutated
                if branch.catchall is not None:
                    mutated |= branch.catchall.mutated
                if mutated and not (mutated & snapshot_attrs):
                    site.ctx.report(
                        "TPU702", node,
                        f"journal table {site.table!r} replays into "
                        f"{sorted(mutated)} but _snapshot() captures none "
                        "of those attributes — compaction permanently "
                        "drops the table",
                        scope=site.scope)
    return []
