"""TPU603 — steady-state recompilation hazard.

XLA compilation costs seconds; a train step costs milliseconds. A jit
cache miss in the steady-state loop is therefore a 1000x hiccup, and
the miss is invisible at the call site — the code "works", just
intermittently three orders of magnitude slower. Statically visible
shapes:

- **loop-varying scalar**: the induction variable of a
  ``for i in range(...)`` / ``enumerate(...)`` loop passed bare (or
  arithmetically derived) into a jitted callable. At a
  ``static_argnums`` position this retraces EVERY iteration by
  construction; at a traced position it rides on weak-type caching
  today but pins the cache to host-scalar semantics (any shape use —
  ``jnp.arange(i)``, ``reshape(i)`` — silently becomes per-step
  recompilation).
- **data-dependent slice**: ``f(x[:n])`` inside a loop with a
  non-constant bound — a new shape per distinct ``n``, a new compile
  per shape. Pad to a bucket instead (the LLM engine's ``_bucket``
  idiom).
- **unhashable static**: a list/dict/set literal passed at a
  ``static_argnums`` position — statics key the cache by VALUE and
  must be hashable; this raises at best and retraces-by-identity at
  worst.

The runtime twin (``sanitize`` compile watch, ``RAY_TPU_SANITIZE=1``)
catches the dynamic remainder: it counts recompiles per function after
``RAY_TPU_SANITIZE_COMPILE_GRACE`` steady-state calls and names the
argument signature that changed."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import jit_util
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name

_LOOP_ITER_TAILS = frozenset({"range", "enumerate"})


def _loop_scalar_targets(node) -> set[str]:
    """Induction variables that are Python ints: for i in range(...) /
    for i, x in enumerate(...)."""
    if not isinstance(node, (ast.For, ast.AsyncFor)):
        return set()
    it = node.iter
    if not isinstance(it, ast.Call):
        return set()
    fname = dotted_name(it.func)
    tail = fname.split(".")[-1] if fname else ""
    if tail not in _LOOP_ITER_TAILS:
        return set()
    target = node.target
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, ast.Tuple) and target.elts and isinstance(
            target.elts[0], ast.Name) and tail == "enumerate":
        return {target.elts[0].id}
    return set()


def _derives_from(expr: ast.AST, names: set[str]) -> str | None:
    """The loop-var name when ``expr`` is it (or pure arithmetic over
    it) — indexing/slicing/calls break the derivation (x[i] is a
    constant-shape load, f(i) may normalize)."""
    if isinstance(expr, ast.Name):
        return expr.id if expr.id in names else None
    if isinstance(expr, ast.BinOp):
        return (_derives_from(expr.left, names)
                or _derives_from(expr.right, names))
    if isinstance(expr, ast.UnaryOp):
        return _derives_from(expr.operand, names)
    return None


def _dynamic_slice_arg(expr: ast.AST) -> str | None:
    """'x[:n]'-style description when ``expr`` slices with a
    non-constant bound."""
    if not isinstance(expr, ast.Subscript):
        return None
    sl = expr.slice
    if not isinstance(sl, ast.Slice):
        return None
    for bound in (sl.lower, sl.upper):
        if bound is None or isinstance(bound, ast.Constant):
            continue
        base = dotted_name(expr.value) or "<arr>"
        bname = dotted_name(bound) or "<expr>"
        return f"{base}[...:{bname}]"
    return None


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, ji: jit_util.ModuleJitIndex):
        super().__init__(ctx)
        self.ji = ji
        self._loop_vars: list[set[str]] = []

    def _klass(self):
        return self._class[-1] if self._class else None

    def _visit_loop(self, node):
        self._loop_vars.append(_loop_scalar_targets(node))
        self.generic_visit(node)
        self._loop_vars.pop()

    def visit_For(self, node):
        self._visit_loop(node)

    def visit_AsyncFor(self, node):
        self._visit_loop(node)

    def visit_While(self, node):
        self._loop_vars.append(set())
        self.generic_visit(node)
        self._loop_vars.pop()

    # ------------------------------------------------------------- calls
    def _callee_info(self, node: ast.Call):
        """(JitInfo, display-name) for calls into known-jitted
        callables: bound vars, decorated defs, local factories."""
        info = self.ji.lookup_callable(node, self._klass())
        name = dotted_name(node.func)
        if info is not None:
            return info, name
        callee = self.ji.mi.resolve_call(node, self._klass())
        if callee is None:
            return None, name
        if callee in self.ji.jit_defs:
            return self.ji.jit_defs[callee], name
        return None, name

    def visit_Call(self, node: ast.Call):
        info, name = self._callee_info(node)
        if info is None:
            self.generic_visit(node)
            return
        static = info.static or ()
        in_loop = bool(self._loop_vars)
        loop_names = set().union(*self._loop_vars) if in_loop else set()
        for pos, arg in enumerate(node.args):
            lv = _derives_from(arg, loop_names) if loop_names else None
            if lv is not None:
                if pos in static:
                    self.ctx.report(
                        "TPU603", node,
                        f"loop variable `{lv}` feeds static_argnums "
                        f"position {pos} of jitted `{name}`: a NEW "
                        "compilation every iteration, by construction "
                        "— statics key the cache by value",
                        scope=self.scope,
                    )
                else:
                    self.ctx.report(
                        "TPU603", node,
                        f"loop variable `{lv}` passed as a Python "
                        f"scalar into jitted `{name}`: the cache key "
                        "rides on weak-type semantics and any shape "
                        "use of it inside the program means a "
                        "recompile per iteration — pass it as a "
                        "traced array (jnp.int32(i)) or hoist it",
                        scope=self.scope,
                    )
            elif in_loop:
                sl = _dynamic_slice_arg(arg)
                if sl is not None:
                    self.ctx.report(
                        "TPU603", node,
                        f"data-dependent slice `{sl}` passed into "
                        f"jitted `{name}` inside a loop: every "
                        "distinct length is a new shape and a new "
                        "compilation — pad to a bucketed length "
                        "instead",
                        scope=self.scope,
                    )
            if pos in static and isinstance(
                    arg, (ast.List, ast.Dict, ast.Set)):
                kind = type(arg).__name__.lower()
                self.ctx.report(
                    "TPU603", node,
                    f"unhashable {kind} literal at static_argnums "
                    f"position {pos} of jitted `{name}`: statics must "
                    "be hashable (use a tuple / frozen mapping)",
                    scope=self.scope,
                )
        self.generic_visit(node)


def run(ctx: FileContext):
    if "jit" not in ctx.source:
        return None
    ji = jit_util.jit_index(ctx)
    if not (ji.jit_vars or ji.jit_defs):
        return None
    _Visitor(ctx, ji).visit(ctx.tree)
    return None


def finalize(states):
    return []
