"""TPU204 — cross-file lock aliasing (closes the carried ROADMAP item).

TPU202 sees a lock only where its *name* is visible. A lock passed as
an argument, stowed in an attribute, or parked in a dict is invisible
there — and those are precisely the locks that end up acquired in an
order nobody audited::

    # a.py                          # b.py
    _table_lock = Lock()            class Flusher:
    f = Flusher(_table_lock)            def __init__(self, lk):
    def update():                           self._lk = lk
        with _table_lock:               def flush(self):
            f.flush()                       with self._lk: ...

TPU204 tracks three alias channels and feeds the resulting edges into
the same order graph TPU202 cycles over:

- **arguments**: ``with param:`` inside a function is a parameterized
  acquisition, instantiated with the concrete lock at every call site
  (transitively — a param forwarded to another function keeps
  resolving).
- **attributes**: ``self._lk = lk`` unifies ``Class._lk`` with
  whatever each constructor call passes.
- **containers**: ``self._locks[k] = Lock()`` / ``with
  self._locks[k]:`` with a VARIABLE key collapse to one summary node
  per container (``Class._locks[]``); a STRING-LITERAL key gets its
  own node (``Class._locks["a"]``), so the ordering between
  ``self._locks["a"]`` and ``self._locks["b"]`` is visible — the
  PR-12 per-container-summary caveat, closed.

Cycles whose every edge was already visible to TPU202 stay TPU202;
only cycles that NEED an aliased edge report here, so one deadlock
never fires twice. Heavily polymorphic bindings (a param fed more than
``_MAX_BINDINGS`` distinct locks) are dropped rather than unioned —
merging unrelated locks would invent cycles."""

from __future__ import annotations

import ast
import dataclasses

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import (
    RULES,
    FileContext,
    ScopeVisitor,
    Violation,
    dotted_name,
)
from ray_tpu._private.lint.pass_locks import _sccs

_LOCKISH = ("lock", "mutex")
_LOCK_FACTORIES = frozenset({
    "Lock", "RLock", "InstrumentedLock", "maybe_lock", "maybe_rlock",
    "allocate_lock",
})
_MAX_BINDINGS = 3


def _is_lockish(name: str) -> bool:
    last = name.split(".")[-1].lower()
    return any(t in last for t in _LOCKISH)


def _is_container_node(canon: str) -> bool:
    """Summary (``C._locks[]``) or per-key (``C._locks["a"]``) node."""
    return canon.endswith("]")


def _container_summary(canon: str) -> str | None:
    """``C._locks["a"]`` → ``C._locks[]``; None for non-key nodes."""
    if canon.endswith("]") and not canon.endswith("[]"):
        return canon[: canon.index("[")] + "[]"
    return None


def _subscript_node(base: str, sl: ast.AST) -> str:
    """Container node name for ``base[sl]``: per-constant-key when the
    subscript is a string literal, the per-container summary otherwise
    (a variable key could be ANY key — one node, soundly merged)."""
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
        return f'{base}["{sl.value}"]'
    return base + "[]"


@dataclasses.dataclass
class _Loc:
    path: str
    line: int
    snippet: str
    allowed: bool


@dataclasses.dataclass
class AliasState:
    mi: dataflow.ModuleIndex = None
    # canonical names assigned a Lock()/RLock() factory result
    lock_defs: set = dataclasses.field(default_factory=set)
    # (canonical_name, Item) — attr/container/name aliases
    aliases: list = dataclasses.field(default_factory=list)
    # fn_qual -> set[Item] acquired directly (with-blocks)
    direct_acq: dict = dataclasses.field(default_factory=dict)
    # (held Item, acquired Item, _Loc) nested acquisitions
    edges: list = dataclasses.field(default_factory=list)
    # (fn, callee, binding, attr_call, held Items, _Loc) calls w/ locks held
    held_calls: list = dataclasses.field(default_factory=list)
    # (fn, callee, binding, attr_call) every call passing a lock item
    call_bindings: list = dataclasses.field(default_factory=list)


class _Visitor(ScopeVisitor):
    """Collects acquisitions/aliases/bindings; items are
    ``("L", canonical)`` for concrete names and ``("P", fn, i)`` for
    the i-th formal parameter of ``fn``."""

    def __init__(self, ctx: FileContext, mi: dataflow.ModuleIndex):
        super().__init__(ctx)
        self.mi = mi
        self.state = AliasState(mi=mi)
        self._held: list = []
        self._params: list[dict[str, int]] = []

    # ---------------------------------------------------------- naming
    def _qualify(self, name: str) -> str:
        return self.mi.qualify(name, self._klass())

    def _klass(self):
        return self._class[-1] if self._class else None

    def _fn_qual(self) -> str:
        klass = self._klass()
        if klass and self._func:
            return f"{klass}.{self._func[-1]}"
        if self._func:
            return f"{self.mi.module}.{self._func[-1]}"
        return f"{self.mi.module}.<module>"

    # --------------------------------------------------------- items
    def _item(self, expr) -> tuple | None:
        if isinstance(expr, ast.Name):
            if self._params and expr.id in self._params[-1]:
                return ("P", self._fn_qual(), self._params[-1][expr.id])
            return ("L", self._qualify(expr.id))
        name = dotted_name(expr)
        if name:
            return ("L", self._qualify(name))
        if isinstance(expr, ast.Subscript):
            base = dotted_name(expr.value)
            if base:
                return ("L", _subscript_node(
                    self._qualify(base), expr.slice))
        return None

    def _loc(self, node) -> _Loc:
        line = getattr(node, "lineno", 1)
        return _Loc(
            path=self.ctx.path,
            line=line,
            snippet=self.ctx.snippet(line),
            allowed=self.ctx.allowed(line, "TPU204"),
        )

    # ------------------------------------------------------- scaffolding
    def _visit_func(self, node):
        params = {a.arg: i for i, a in enumerate(
            node.args.posonlyargs + node.args.args)}
        for j, a in enumerate(node.args.kwonlyargs):
            params.setdefault(a.arg, len(node.args.posonlyargs)
                              + len(node.args.args) + j)
        self._params.append(params)
        held, self._held = self._held, []
        super()._visit_func(node)
        self._held = held
        self._params.pop()

    def visit_Lambda(self, node):
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    # ------------------------------------------------------ acquisitions
    def _with_item(self, expr) -> tuple | None:
        if isinstance(expr, ast.Call):
            # factory style: self._pool_lock(name) — lockish calls only
            name = dotted_name(expr.func)
            if name and _is_lockish(name):
                return ("L", self._qualify(name))
            return None
        return self._item(expr)

    def _enter_with(self, node):
        fn = self._fn_qual()
        acquired = []
        for item in node.items:
            it = self._with_item(item.context_expr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            if it is None:
                continue
            self.state.direct_acq.setdefault(fn, set()).add(it)
            loc = self._loc(node)
            for held in self._held:
                if held != it:
                    self.state.edges.append((held, it, loc))
            self._held.append(it)
            acquired.append(it)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    def visit_With(self, node):
        self._enter_with(node)

    def visit_AsyncWith(self, node):
        self._enter_with(node)

    # ------------------------------------------------------ assignments
    def _maybe_alias(self, target, value):
        # target canonical
        tgt = None
        name = dotted_name(target)
        if name:
            tgt = self._qualify(name)
        elif isinstance(target, ast.Subscript):
            base = dotted_name(target.value)
            if base:
                tgt = _subscript_node(self._qualify(base), target.slice)
        if tgt is None:
            return
        if isinstance(value, ast.Call):
            fname = dotted_name(value.func)
            if fname and fname.split(".")[-1] in _LOCK_FACTORIES:
                self.state.lock_defs.add(tgt)
            return
        it = self._item(value)
        if it is not None:
            self.state.aliases.append((tgt, it))

    def visit_Assign(self, node: ast.Assign):
        for target in node.targets:
            self._maybe_alias(target, node.value)
        self.generic_visit(node)

    # ----------------------------------------------------------- calls
    def visit_Call(self, node: ast.Call):
        callee = self.mi.resolve_call(node, self._klass())
        if callee is not None:
            binding = {}
            for pos, arg in enumerate(node.args):
                it = self._item(arg)
                if it is not None:
                    binding[pos] = it
            kwbinding = {}
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                it = self._item(kw.value)
                if it is not None:
                    kwbinding[kw.arg] = it
            attr_call = isinstance(node.func, ast.Attribute)
            if binding or kwbinding:
                self.state.call_bindings.append(
                    (self._fn_qual(), callee, binding, kwbinding,
                     attr_call))
            if self._held:
                self.state.held_calls.append(
                    (self._fn_qual(), callee, binding, kwbinding,
                     attr_call, list(self._held), self._loc(node)))
        self.generic_visit(node)


def run(ctx: FileContext):
    # No textual prefilter here: the whole point of the alias pass is
    # locks living under names that DON'T look like locks.
    mi = dataflow.index(ctx)
    v = _Visitor(ctx, mi)
    v.visit(ctx.tree)
    return v.state


# --------------------------------------------------------------------------
# Linking
# --------------------------------------------------------------------------


class _UnionFind:
    def __init__(self):
        self.parent: dict[str, str] = {}
        self.size: dict[str, int] = {}

    def find(self, x: str) -> str:
        self.parent.setdefault(x, x)
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a: str, b: str):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[ra] = rb
            self.size[rb] = self.size.get(rb, 1) + self.size.get(ra, 1)

    def merged(self, x: str) -> bool:
        return self.size.get(self.find(x), 1) > 1


def _resolve_binding(callee_info, binding, kwbinding, attr_call, is_ctor):
    """Map arg positions / kw names onto the callee's PARAM indexes."""
    if callee_info is None:
        return {}
    params = callee_info.params
    offset = 1 if (params and params[0] in ("self", "cls")
                   and (attr_call or is_ctor)) else 0
    out = {}
    for pos, item in binding.items():
        out[pos + offset] = item
    for kwname, item in kwbinding.items():
        if kwname in params:
            out[params.index(kwname)] = item
    return out


def finalize(states):
    states = [st for st in states if st is not None]
    if not states:
        return []
    program = dataflow.Program([st.mi for st in states])

    # Constructor resolution: a call to `module.C` is `C.__init__`.
    ctor_map = {}
    for qual in program.functions:
        cls, _, meth = qual.partition(".")
        if meth == "__init__":
            ctor_map.setdefault(cls, qual)

    def resolve_callee(callee):
        """(resolved qual, is_ctor) — or (None, False) if unknown."""
        if callee in program.functions:
            return callee, False
        tail = callee.split(".")[-1]
        if tail in ctor_map:
            return ctor_map[tail], True
        return None, False

    # Lock-relevance pre-filter: bindings of config objects and other
    # non-lock values would flood the param lattice — keep only items
    # that can plausibly BE a lock (lockish name, known Lock() def,
    # container node, alias target, or a formal param).
    lock_names_early: set[str] = set()
    alias_targets: set[str] = set()
    for st in states:
        lock_names_early |= st.lock_defs
        for tgt, _ in st.aliases:
            alias_targets.add(tgt)

    def _relevant(item) -> bool:
        if item[0] == "P":
            return True
        c = item[1]
        return (_is_lockish(c) or c in lock_names_early
                or c in alias_targets or _is_container_node(c))

    # ---------------------------------------------------- param values
    # (fn, idx) -> set of "L" canonicals / ("P", fn', idx') refs
    param_values: dict[tuple, set] = {}
    norm_calls = []   # (caller, callee_qual, {param_idx: Item})
    for st in states:
        for (caller, callee, binding, kwbinding, attr_call) \
                in st.call_bindings:
            q, is_ctor = resolve_callee(callee)
            if q is None:
                continue
            b = _resolve_binding(program.functions[q], binding,
                                 kwbinding, attr_call, is_ctor)
            b = {i: it for i, it in b.items() if _relevant(it)}
            if b:
                norm_calls.append((caller, q, b))
                for idx, item in b.items():
                    param_values.setdefault((q, idx), set()).add(item)

    # Fixpoint: a param bound to another fn's param keeps resolving.
    def ground_params(item, seen=None) -> set:
        """Item -> set of concrete 'L' canonicals."""
        if item[0] == "L":
            return {item[1]}
        if seen is None:
            seen = set()
        key = (item[1], item[2])
        if key in seen:
            return set()
        seen.add(key)
        out = set()
        for bound in param_values.get(key, ()):
            out |= ground_params(bound, seen)
            if len(out) > _MAX_BINDINGS:
                return set()   # too polymorphic — dropping beats lying
        return out

    # -------------------------------------------------------- lockhood
    uf = _UnionFind()
    lock_names: set[str] = set()
    for st in states:
        lock_names |= st.lock_defs
    alias_pairs = []
    for st in states:
        for tgt, item in st.aliases:
            alias_pairs.append((tgt, item))
    for tgt, item in alias_pairs:
        grounded = ground_params(item)
        if not (0 < len(grounded) <= _MAX_BINDINGS):
            continue
        # Union only lock-relevant aliases: `self.cfg = cfg` must not
        # stitch arbitrary config names into the lock graph.
        if not (_is_lockish(tgt) or tgt in lock_names
                or any(_is_lockish(g) or g in lock_names
                       for g in grounded)):
            continue
        for g in grounded:
            uf.union(tgt, g)

    lock_reps = set()
    for c in set(uf.parent) | lock_names:
        if _is_lockish(c) or c in lock_names:
            lock_reps.add(uf.find(c))

    # Lockhood flows between a container's summary node and its
    # per-key nodes: `self._m["a"] = Lock()` makes a variable-key
    # acquisition of the same dict (`self._m[k]` → `C._m[]`) a lock,
    # and a summary-level Lock() def covers every literal key.
    per_key_lock_containers = {
        _container_summary(c) for c in lock_names
        if _container_summary(c) is not None
    }

    def is_lock(canon: str) -> bool:
        if (_is_lockish(canon) or canon in lock_names
                or uf.find(canon) in lock_reps):
            return True
        if canon.endswith("[]") and canon in per_key_lock_containers:
            return True
        summ = _container_summary(canon)
        return summ is not None and (summ in lock_names
                                     or _is_lockish(summ))

    # ----------------------------------------------------- acq closure
    acq: dict[str, set] = {}
    for st in states:
        for fn, items in st.direct_acq.items():
            acq.setdefault(fn, set()).update(items)
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for caller, callee, binding in norm_calls:
            got = acq.setdefault(caller, set())
            before = len(got)
            for item in acq.get(callee, ()):
                if item[0] == "L":
                    got.add(item)
                elif item[1] == callee and item[2] in binding:
                    got.add(binding[item[2]])
            if len(got) != before:
                changed = True

    # --------------------------------------------------------- edges
    # (rep_a, rep_b) -> (loc, aliased)
    grounded_edges: dict[tuple, tuple] = {}

    def add_edge(a_item, b_item, loc, via_alias):
        for a in ground_params(a_item):
            for b in ground_params(b_item):
                if a == b or not (is_lock(a) and is_lock(b)):
                    continue
                aliased = (
                    via_alias
                    or a_item[0] == "P" or b_item[0] == "P"
                    or _is_container_node(a) or _is_container_node(b)
                    or uf.merged(a) or uf.merged(b)
                    or not _is_lockish(a) or not _is_lockish(b)
                )
                key = (uf.find(a), uf.find(b))
                if key[0] == key[1]:
                    continue
                prev = grounded_edges.get(key)
                if prev is None or (aliased and not prev[1]):
                    grounded_edges[key] = (loc, aliased)

    for st in states:
        for held, it, loc in st.edges:
            add_edge(held, it, loc, False)
        for (caller, callee, binding, kwbinding, attr_call, held,
             loc) in st.held_calls:
            q, is_ctor = resolve_callee(callee)
            if q is None:
                continue
            b = _resolve_binding(program.functions[q], binding,
                                 kwbinding, attr_call, is_ctor)
            for item in acq.get(q, ()):
                resolved = item
                via_param = False
                if item[0] == "P":
                    if item[1] == q and item[2] in b:
                        resolved = b[item[2]]
                        via_param = True
                    else:
                        continue
                # A callee acquiring a NAMED lock is visible to TPU202's
                # own call closure — only param-instantiated locks make
                # the edge "aliased".
                for h in held:
                    add_edge(h, resolved, loc, via_param)

    # ------------------------------------------------------------ SCC
    graph: dict[str, set[str]] = {}
    for a, b in grounded_edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    violations = []
    for comp in _sccs(graph):
        comp_set = set(comp)
        comp_edges = [(k, grounded_edges[k]) for k in grounded_edges
                      if k[0] in comp_set and k[1] in comp_set]
        if not any(aliased for _, (_, aliased) in comp_edges):
            continue   # fully name-visible: TPU202's report, not ours
        comp_edges.sort(key=lambda kv: (kv[1][0].path, kv[1][0].line))
        anchor = next(
            (loc for _, (loc, aliased) in comp_edges
             if aliased and not loc.allowed), None)
        if anchor is None:
            continue
        cycle = " -> ".join(comp + [comp[0]])
        violations.append(Violation(
            rule="TPU204",
            name=RULES["TPU204"],
            path=anchor.path,
            line=anchor.line,
            col=0,
            message=(
                f"lock-order cycle {cycle} through an ALIASED lock "
                "(passed as argument / stored in attribute or "
                "container): two threads taking these locks in "
                "opposite orders deadlock, and no single file shows "
                "the inversion — pick one global order"
            ),
            scope="|".join(comp),
            snippet=anchor.snippet,
        ))
    return violations
