"""tpulint interprocedural dataflow engine.

Three layers, each consumed by the flow-sensitive passes
(TPU103/TPU104/TPU203/TPU204/TPU404):

- :class:`ModuleIndex` — one parsed file's symbol table: function
  definitions by qualname (``Class.method`` / ``module.func``, the
  same unification TPU202 uses), the import map (``from a import x``
  → ``x`` belongs to module ``a``), and every resolvable call site per
  function. Built once per file and cached on the
  :class:`~ray_tpu._private.lint.core.FileContext` so the five passes
  share one walk.
- :class:`Program` — the module indexes stitched into a program-level
  call graph with ``closure()`` (which functions transitively reach a
  seed set — how TPU103 finds *wrapped* collectives) and reverse
  edges (how ``--changed`` finds interprocedural neighbors).
- :class:`FlowWalker` — a small abstract interpreter over function
  bodies: branch-forking ``if``/``else``, loop bodies walked twice (so
  a fact established at the bottom of a loop is visible at its top —
  the overwritten-while-pending shape), ``try`` bodies feeding their
  handlers the merged mid-body state (exception paths see every prefix
  of the protected region), and early exits (``return``/``raise``/
  ``break``/``continue``) delivered to an ``on_exit`` hook. Passes
  subclass it with their own :class:`PathState`.

The engine is still a *linter's* dataflow: names are unified
syntactically (``self.x`` → ``Class.x``), not through object identity,
and containers collapse to one summary node per container. Precision
comes from the pragma escape hatch; soundness comes from the runtime
sanitizer twins in ``ray_tpu/_private/sanitize.py``.
"""

from __future__ import annotations

import ast
import dataclasses

from ray_tpu._private.lint.core import FileContext, dotted_name, iter_tree, iter_children

# --------------------------------------------------------------------------
# Module indexing
# --------------------------------------------------------------------------


@dataclasses.dataclass
class CallSite:
    """One resolvable call: ``callee`` is the program-level qualname
    (``Class.method`` or ``module.func``), ``node`` the ast.Call."""

    callee: str
    node: ast.Call


@dataclasses.dataclass
class FunctionInfo:
    qual: str                     # "Class.method" | "module.func"
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    ctx: FileContext
    class_name: str | None
    params: list[str]
    calls: list[CallSite] = dataclasses.field(default_factory=list)

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


class ModuleIndex:
    """Symbol table + call sites for one parsed module."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module = ctx.module
        # `from a.b import x as y` → imports["y"] == "b" (tail module):
        # the same tail-module unification TPU202 established, so a
        # name reached through an import collides with its definition.
        self.imports: dict[str, str] = {}
        # import a.b.c as m → module_aliases["m"] == "c"
        self.module_aliases: dict[str, str] = {}
        self.functions: dict[str, FunctionInfo] = {}
        # qualified var/attr name -> class name, from `x = Klass(...)`
        # assignments (one level of "type inference" so `x.method()`
        # resolves — enough for the singleton/member idiom this
        # codebase uses everywhere).
        self.var_types: dict[str, str] = {}
        self._collect_imports(ctx.tree)
        self._collect_types(ctx.tree)
        self._collect_functions(ctx.tree)

    # ------------------------------------------------------------- imports
    def _collect_imports(self, tree: ast.Module) -> None:
        for node in self.ctx.nodes:
            if isinstance(node, ast.ImportFrom) and node.module:
                src = node.module.split(".")[-1]
                for alias in node.names:
                    if alias.name != "*":
                        self.imports[alias.asname or alias.name] = src
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    tail = alias.name.split(".")[-1]
                    self.module_aliases[
                        alias.asname or alias.name.split(".")[0]
                    ] = tail

    # -------------------------------------------------------------- types
    def _collect_types(self, tree: ast.Module) -> None:
        def walk(node, class_name):
            for child in iter_children(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, class_name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call):
                    fname = dotted_name(child.value.func)
                    tail = fname.split(".")[-1] if fname else ""
                    if not tail or not tail[0].isupper():
                        continue
                    for target in child.targets:
                        tname = dotted_name(target)
                        if tname:
                            self.var_types[
                                self.qualify(tname, class_name)] = tail
                else:
                    walk(child, class_name)

        walk(tree, None)

    # ----------------------------------------------------------- functions
    def _collect_functions(self, tree: ast.Module) -> None:
        def walk(node, class_name: str | None):
            for child in iter_children(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    if class_name:
                        qual = f"{class_name}.{child.name}"
                    else:
                        qual = f"{self.module}.{child.name}"
                    params = [a.arg for a in child.args.args
                              + child.args.posonlyargs
                              + child.args.kwonlyargs]
                    info = FunctionInfo(
                        qual=qual, node=child, ctx=self.ctx,
                        class_name=class_name, params=params,
                    )
                    # Innermost wins on duplicate quals (overloads by
                    # TYPE_CHECKING etc.) — harmless for a linter.
                    self.functions[qual] = info
                    self._collect_calls(info)
                    walk(child, class_name)  # nested defs keep class

        walk(tree, None)

    def _collect_calls(self, info: FunctionInfo) -> None:
        for node in iter_tree(info.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(node, info.class_name)
            if callee is not None:
                info.calls.append(CallSite(callee=callee, node=node))

    # ------------------------------------------------------------ resolve
    def resolve_call(self, call: ast.Call,
                     class_name: str | None) -> str | None:
        """Program-level qualname of the callee, or None when the target
        is dynamic (subscripts, call results, foreign attributes)."""
        func = call.func
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name):
                base = func.value.id
                if base in ("self", "cls"):
                    if class_name:
                        return f"{class_name}.{func.attr}"
                    return None
                if base in self.module_aliases:
                    return f"{self.module_aliases[base]}.{func.attr}"
                if base in self.imports:
                    # `from pkg import mod` then `mod.fn()` — attribute
                    # off an imported *module* name.
                    return f"{base}.{func.attr}"
            # one level of type inference: `f = Flusher(...)` then
            # `f.flush()` (or `self._f.flush()`) resolves to
            # Flusher.flush.
            recv = dotted_name(func.value)
            if recv:
                cls = self.var_types.get(self.qualify(recv, class_name))
                if cls:
                    return f"{cls}.{func.attr}"
            return None
        if isinstance(func, ast.Name):
            src = self.imports.get(func.id, self.module)
            return f"{src}.{func.id}"
        return None

    def qualify(self, name: str, class_name: str | None) -> str:
        """Unify a dotted value name program-wide (the TPU202 lock
        convention): ``self.x`` → ``Class.x``; imported → ``src.x``;
        bare → ``module.x``."""
        parts = name.split(".")
        if parts[0] in ("self", "cls") and class_name:
            return f"{class_name}.{'.'.join(parts[1:])}"
        if parts[0] in self.imports:
            return f"{self.imports[parts[0]]}.{name}"
        if parts[0] in self.module_aliases:
            tail = self.module_aliases[parts[0]]
            return f"{tail}.{'.'.join(parts[1:])}"
        return f"{self.module}.{name}"


def index(ctx: FileContext) -> ModuleIndex:
    """Shared per-file index, cached on the context: five passes, one
    symbol-table walk."""
    cached = getattr(ctx, "_df_index", None)
    if cached is None:
        cached = ModuleIndex(ctx)
        ctx._df_index = cached
    return cached


# --------------------------------------------------------------------------
# Program: cross-module call graph
# --------------------------------------------------------------------------


class Program:
    """The analyzed file set as one call graph."""

    def __init__(self, indexes):
        self.indexes: list[ModuleIndex] = list(indexes)
        self.functions: dict[str, FunctionInfo] = {}
        self.calls: dict[str, set[str]] = {}
        self.callers: dict[str, set[str]] = {}
        for mi in self.indexes:
            for qual, info in mi.functions.items():
                self.functions.setdefault(qual, info)
                edges = self.calls.setdefault(qual, set())
                for cs in info.calls:
                    edges.add(cs.callee)
                    self.callers.setdefault(cs.callee, set()).add(qual)

    def closure(self, seeds: set[str]) -> set[str]:
        """Functions that transitively CALL INTO ``seeds`` (callers of
        callers …), including the seeds themselves. Fixpoint over the
        reverse edges — how "this helper eventually issues a
        collective" propagates outward."""
        out = set(seeds)
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            for caller in self.callers.get(fn, ()):
                if caller not in out:
                    out.add(caller)
                    frontier.append(caller)
        return out


# --------------------------------------------------------------------------
# Flow-sensitive walker
# --------------------------------------------------------------------------


class PathState:
    """Base abstract state; passes subclass. ``fork()`` must deep-copy
    anything mutated; ``merge()`` joins two paths in place."""

    def fork(self) -> "PathState":  # pragma: no cover - interface
        raise NotImplementedError

    def merge(self, other: "PathState") -> None:  # pragma: no cover
        raise NotImplementedError


class FlowWalker:
    """Structured abstract interpreter over one function body.

    Subclass hooks (all optional):

    - ``on_stmt(stmt, state)`` — every statement before dispatch.
    - ``on_assign(stmt, state)`` / ``on_call(call, state)`` /
      ``on_await(node, state)`` — events in evaluation order.
    - ``on_branch(test, state, taken)`` — entering an ``if`` arm;
      ``taken`` is False for the else arm.
    - ``on_with(item, state, is_async)`` → optional token;
      ``on_with_exit(token, state)`` after the body.
    - ``on_exit(state, node, kind)`` — ``kind`` in {"return", "raise",
      "break", "continue", "fall"}; called once per explicit exit and
      once at the fall-off-the-end join.

    The walker returns None from a body walk when every path exited.
    Loop bodies are walked twice so facts from iteration N reach
    iteration N+1; ``try`` handlers start from the merge of every
    mid-body state (any prefix of the body may have run when the
    exception fired).
    """

    def walk_function(self, fn_node, state: PathState) -> None:
        self._finally_depth = 0
        self._finally_stack: list[list] = []
        end = self._walk_body(fn_node.body, state)
        if end is not None:
            self.on_exit(end, fn_node, "fall")

    def _run_pending_finallys(self, state):
        """An explicit exit inside ``try`` suites runs every enclosing
        ``finally`` before leaving — cleanups there must count."""
        pending, self._finally_stack = self._finally_stack, []
        try:
            for fb in reversed(pending):
                if state is None:
                    break
                self._finally_depth += 1
                state = self._walk_body(fb, state)
                self._finally_depth -= 1
        finally:
            self._finally_stack = pending
        return state

    @property
    def in_finally(self) -> bool:
        """True while walking a ``finally`` suite — the one place a
        cleanup call is exception-safe without a ``with``."""
        return getattr(self, "_finally_depth", 0) > 0

    # ------------------------------------------------------------- hooks
    def on_stmt(self, stmt, state):  # pragma: no cover - default
        pass

    def on_assign(self, stmt, state):
        pass

    def on_call(self, call, state):
        pass

    def on_await(self, node, state):
        pass

    def on_branch(self, test, state, taken: bool):
        return None

    def on_branch_exit(self, token, state):
        pass

    def on_if_join(self, stmt, state, then_exited: bool,
                   else_exited: bool):
        """After an ``if``: ``state`` is the merged survivor (None when
        both arms exited). ``then_exited``/``else_exited`` say which
        arms left the function — the rank-dependent-early-exit shape."""
        pass

    def on_with(self, item, state, is_async: bool):
        return None

    def on_with_exit(self, token, state):
        pass

    def on_exit(self, state, node, kind: str):
        pass

    # ---------------------------------------------------------- traversal
    def _visit_calls(self, node, state) -> None:
        """Fire on_call/on_await for every call in an expression,
        skipping nested function/lambda bodies (they do not run
        here). Order is structural, not evaluation order — the current
        passes only need the set of calls on the path."""
        if node is None:
            return
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                self.on_call(n, state)
            elif isinstance(n, ast.Await):
                self.on_await(n, state)
            stack.extend(iter_children(n))

    def _walk_body(self, stmts, state):
        for stmt in stmts:
            if state is None:
                break
            state = self._walk_stmt(stmt, state)
        return state

    def _walk_stmt(self, stmt, state):
        self.on_stmt(stmt, state)

        if isinstance(stmt, (ast.Return, ast.Raise)):
            self._visit_calls(getattr(stmt, "value", None)
                              or getattr(stmt, "exc", None), state)
            kind = "return" if isinstance(stmt, ast.Return) else "raise"
            state = self._run_pending_finallys(state)
            if state is not None:
                self.on_exit(state, stmt, kind)
            return None

        if isinstance(stmt, (ast.Break, ast.Continue)):
            kind = "break" if isinstance(stmt, ast.Break) else "continue"
            state = self._run_pending_finallys(state)
            if state is not None:
                self.on_exit(state, stmt, kind)
            return None

        if isinstance(stmt, ast.If):
            self._visit_calls(stmt.test, state)
            then_state = state.fork()
            t_token = self.on_branch(stmt.test, then_state, True)
            then_end = self._walk_body(stmt.body, then_state)
            if then_end is not None:
                self.on_branch_exit(t_token, then_end)
            else_state = state
            e_token = self.on_branch(stmt.test, else_state, False)
            else_end = self._walk_body(stmt.orelse, else_state)
            if else_end is not None:
                self.on_branch_exit(e_token, else_end)
            if then_end is None and else_end is None:
                out = None
            elif then_end is None:
                out = else_end
            elif else_end is None:
                out = then_end
            else:
                else_end.merge(then_end)
                out = else_end
            self.on_if_join(stmt, out, then_end is None, else_end is None)
            return out

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._visit_calls(stmt.test, state)
            else:
                self._visit_calls(stmt.iter, state)
            # Two passes over the body: the second starts from the
            # first's end state, so "assigned at the bottom, observed
            # at the top" (handle overwritten next iteration) is seen.
            body_end = self._walk_body(stmt.body, state.fork())
            if body_end is not None:
                second = self._walk_body(stmt.body, body_end.fork())
                if second is not None:
                    body_end = second
            # Loop may run zero times: merge body-exit into fallthrough.
            if body_end is not None:
                state.merge(body_end)
            return self._walk_body(stmt.orelse, state)

        if isinstance(stmt, ast.Try):
            entry = state.fork()
            mid_states = [entry.fork()]
            if stmt.finalbody:
                self._finally_stack.append(stmt.finalbody)

            body_state = state
            for s in stmt.body:
                if body_state is None:
                    break
                body_state = self._walk_stmt(s, body_state)
                if body_state is not None:
                    mid_states.append(body_state.fork())

            # Handler entry: ANY prefix of the body may have completed.
            handler_entry = mid_states[0]
            for ms in mid_states[1:]:
                handler_entry.merge(ms)

            exits = []
            if body_state is not None:
                else_state = self._walk_body(stmt.orelse, body_state)
                if else_state is not None:
                    exits.append(else_state)
            for handler in stmt.handlers:
                h_end = self._walk_body(handler.body, handler_entry.fork())
                if h_end is not None:
                    exits.append(h_end)

            if stmt.finalbody:
                self._finally_stack.pop()
            if not exits:
                # Every path out of the try exited the function; the
                # finally still runs, walk it for its events.
                if stmt.finalbody:
                    self._finally_depth += 1
                    self._walk_body(stmt.finalbody, handler_entry.fork())
                    self._finally_depth -= 1
                return None
            out = exits[0]
            for e in exits[1:]:
                out.merge(e)
            if not stmt.finalbody:
                return out
            self._finally_depth += 1
            out = self._walk_body(stmt.finalbody, out)
            self._finally_depth -= 1
            return out

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            tokens = []
            for item in stmt.items:
                self._visit_calls(item.context_expr, state)
                tokens.append(self.on_with(
                    item, state, isinstance(stmt, ast.AsyncWith)))
            end = self._walk_body(stmt.body, state)
            if end is not None:
                for token in reversed(tokens):
                    self.on_with_exit(token, end)
            return end

        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._visit_calls(stmt.value, state)
            self.on_assign(stmt, state)
            return state

        if isinstance(stmt, ast.Expr):
            self._visit_calls(stmt.value, state)
            return state

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # A nested definition does not execute here.
            return state

        # Anything else (Assert, Delete, Global, …): surface its calls.
        self._visit_calls(stmt, state)
        return state
