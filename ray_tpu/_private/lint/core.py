"""tpulint core: file walking, pragma parsing, violation model.

The passes are deliberately heuristic — name-based lock detection,
token-based rank detection — tuned against THIS codebase's idioms
(``self._lock``, ``col.allreduce``, ``_on_<method>`` RPC handlers).
Since the v2 engine (``dataflow.py``) the flow-sensitive passes
(TPU103/104/203/204/404) add interprocedural reach — call-graph
closure, argument/attribute/container lock aliasing, and a
branch/loop/early-return-aware abstract interpreter — but names are
still unified syntactically; precision ultimately comes from the
pragma escape hatch, and runtime truth from ``sanitize.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*allow\(\s*([A-Za-z0-9_,\s-]+?)\s+reason=[^)]+\)"
)

#: Rule id → pragma name. A pragma may name either form.
RULES = {
    "TPU101": "collective-divergence",
    "TPU102": "collective-divergence",
    "TPU103": "rank-divergence-flow",
    "TPU104": "dropped-handle",
    "TPU201": "blocking-under-lock",
    "TPU202": "lock-order",
    "TPU203": "async-lock",
    "TPU204": "lock-alias",
    "TPU301": "broad-except",
    "TPU401": "metric-in-function",
    "TPU402": "span-leak",
    "TPU403": "unbounded-metric-label",
    "TPU404": "resource-pairing",
    "TPU501": "rpc-reentrancy",
    "TPU601": "host-sync-in-hot-path",
    "TPU602": "jit-side-effect",
    "TPU603": "recompilation-hazard",
    "TPU604": "donation-misuse",
    "TPU605": "jit-boundary-divergence",
    "TPU701": "rpc-contract-drift",
    "TPU702": "journal-replay-completeness",
    "TPU703": "knob-discipline",
    "TPU704": "pubsub-channel-discipline",
    "TPU705": "metric-schema-drift",
}

# Generated / vendored files nobody hand-edits.
DEFAULT_EXCLUDES = ("_pb2.py", "_pb2_grpc.py")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str       # "TPU301"
    name: str       # "broad-except"
    path: str       # as given to the analyzer (usually repo-relative)
    line: int
    col: int
    message: str
    scope: str = "<module>"   # enclosing Class.function
    snippet: str = ""         # stripped source line

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline: edits above a
        pinned violation must not make it read as new."""
        return "|".join(
            (self.rule, self.path.replace(os.sep, "/"), self.scope,
             self.snippet)
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.name}] {self.message}"
        )


def parse_pragmas(lines: list[str]) -> dict[int, set[str]]:
    """1-based line → set of allowed rule tokens (rule ids or names).

    A pragma without ``reason=`` is intentionally inert: the reason IS
    the review artifact (why this broad except / blocking call is
    deliberate), so an unexplained allow must not suppress anything.
    """
    out: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        if "tpulint" not in text:
            continue
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        tokens = {t.strip() for t in m.group(1).split(",") if t.strip()}
        out[i] = tokens
    return out


def iter_tree(node: ast.AST):
    """``ast.walk`` with the deque/iter_child_nodes generator overhead
    stripped (~2x faster; same node set, order unspecified). The full
    sweep walks every tree ~10 times across twenty passes — this is the
    analyzer's hottest primitive."""
    stack = [node]
    pop = stack.pop
    push = stack.append
    isinst = isinstance
    _AST = ast.AST
    while stack:
        n = pop()
        yield n
        for f in n._fields:
            v = getattr(n, f, None)
            if type(v) is list:
                for c in v:
                    if isinst(c, _AST):
                        push(c)
            elif isinst(v, _AST):
                push(v)


def iter_children(node: ast.AST):
    """``ast.iter_child_nodes`` without the chained iter_fields
    generator — same children, ~2x faster."""
    isinst = isinstance
    _AST = ast.AST
    for f in node._fields:
        v = getattr(node, f, None)
        if type(v) is list:
            for c in v:
                if isinst(c, _AST):
                    yield c
        elif isinst(v, _AST):
            yield v


class FileContext:
    """One parsed file plus everything a pass needs to report on it."""

    def __init__(self, path: str, source: str, display_path: str | None = None,
                 strict: bool = False):
        self.path = display_path or path
        self.real_path = path
        self.strict = strict
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.pragmas = parse_pragmas(self.lines)
        self.module = os.path.basename(path)[:-3] if path.endswith(
            ".py") else os.path.basename(path)
        self.violations: list[Violation] = []
        self._nodes: list[ast.AST] | None = None

    @property
    def nodes(self) -> list[ast.AST]:
        """Every node in the tree, walked once and cached — passes that
        scan the whole module iterate this instead of re-walking."""
        if self._nodes is None:
            self._nodes = list(iter_tree(self.tree))
        return self._nodes

    def allowed(self, line: int, rule: str) -> bool:
        """Pragma on the statement line or the line directly above."""
        tokens = self.pragmas.get(line, set()) | self.pragmas.get(
            line - 1, set())
        return bool(tokens & {rule, RULES.get(rule, ""), "all"})

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def report(self, rule: str, node: ast.AST, message: str,
               scope: str = "<module>") -> None:
        line = getattr(node, "lineno", 1)
        if self.allowed(line, rule):
            return
        self.violations.append(Violation(
            rule=rule,
            name=RULES[rule],
            path=self.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            scope=scope,
            snippet=self.snippet(line),
        ))


def dotted_name(node: ast.AST) -> str:
    """'self._lock', 'time.sleep', 'col.allreduce' — '' if not a pure
    Name/Attribute chain (calls, subscripts break the chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class ScopeVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing Class.function qualname.

    ``visit``/``generic_visit`` are reimplemented without the stdlib's
    per-node string concat + iter_fields generators: every pass visitor
    in the package subclasses this, and the dispatch overhead was the
    second-hottest line in the full sweep after ``ast.walk``.
    """

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self._class: list[str] = []
        self._func: list[str] = []
        self._vcache: dict = {}

    def visit(self, node):
        cls = node.__class__
        method = self._vcache.get(cls, False)
        if method is False:
            method = getattr(self, "visit_" + cls.__name__, None)
            self._vcache[cls] = method
        if method is not None:
            return method(node)
        return self.generic_visit(node)

    def generic_visit(self, node):
        visit = self.visit
        isinst = isinstance
        _AST = ast.AST
        for f in node._fields:
            v = getattr(node, f, None)
            if type(v) is list:
                for c in v:
                    if isinst(c, _AST):
                        visit(c)
            elif isinst(v, _AST):
                visit(v)

    def visit_Constant(self, node):
        """Constants are leaves; shadow the stdlib's per-node
        deprecation shim (it's ~5% of a full sweep by itself)."""

    @property
    def scope(self) -> str:
        parts = self._class[-1:] + self._func[-1:]
        return ".".join(parts) if parts else "<module>"

    @property
    def in_function(self) -> bool:
        return bool(self._func)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _visit_func(self, node):
        self._func.append(node.name)
        self.enter_function(node)
        self.generic_visit(node)
        self.exit_function(node)
        self._func.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node)

    def enter_function(self, node) -> None:  # hooks for passes
        pass

    def exit_function(self, node) -> None:
        pass


def iter_python_files(paths, excludes: tuple[str, ...] = DEFAULT_EXCLUDES):
    """Yield .py files under the given files/directories, skipping
    __pycache__ and excluded suffixes, in sorted order."""
    seen = set()
    for root in paths:
        if os.path.isfile(root):
            if root not in seen:
                seen.add(root)
                yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                if any(fn.endswith(suf) for suf in excludes):
                    continue
                p = os.path.join(dirpath, fn)
                if p not in seen:
                    seen.add(p)
                    yield p


def _passes():
    # Imported lazily so `import ray_tpu._private.lint.core` works while
    # a pass module is mid-edit (and to keep import cost off the
    # non-lint path).
    from ray_tpu._private.lint import (
        pass_async_locks,
        pass_collective,
        pass_donation,
        pass_exceptions,
        pass_handles,
        pass_host_sync,
        pass_jit_divergence,
        pass_jit_effects,
        pass_journal,
        pass_knobs,
        pass_lock_alias,
        pass_locks,
        pass_metric_schema,
        pass_metrics,
        pass_pairing,
        pass_pubsub,
        pass_rank_flow,
        pass_recompile,
        pass_rpc,
        pass_rpc_contract,
    )
    return [pass_collective, pass_exceptions, pass_locks, pass_metrics,
            pass_rpc, pass_rank_flow, pass_handles, pass_async_locks,
            pass_lock_alias, pass_pairing, pass_host_sync,
            pass_jit_effects, pass_recompile, pass_donation,
            pass_jit_divergence, pass_rpc_contract, pass_journal,
            pass_knobs, pass_pubsub, pass_metric_schema]


def analyze_source(source: str, path: str = "<string>",
                   strict: bool = False) -> list[Violation]:
    """Run every pass over one in-memory module (fixture tests)."""
    ctx = FileContext(path, source, strict=strict)
    for mod in _passes():
        state = mod.run(ctx)
        if state is not None:
            ctx.violations.extend(mod.finalize([state]))
    ctx.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return ctx.violations


def analyze_file(path: str, display_path: str | None = None,
                 strict: bool = False) -> list[Violation]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    ctx = FileContext(path, source, display_path=display_path, strict=strict)
    for mod in _passes():
        state = mod.run(ctx)
        if state is not None:
            ctx.violations.extend(mod.finalize([state]))
    ctx.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return ctx.violations


def analyze_paths(paths, relative_to: str | None = None,
                  excludes: tuple[str, ...] = DEFAULT_EXCLUDES,
                  strict: bool = False):
    """Analyze every .py file under ``paths``.

    Returns (violations, errors) where errors is a list of
    (path, message) for unparseable files — reported, never fatal:
    one syntax-broken WIP file must not hide the report for the rest
    of the tree.
    """
    contexts: list[FileContext] = []
    errors: list[tuple[str, str]] = []
    for path in iter_python_files(paths, excludes=excludes):
        display = path
        if relative_to:
            display = os.path.relpath(path, relative_to)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            contexts.append(FileContext(path, source, display_path=display,
                                        strict=strict))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((display, f"{type(e).__name__}: {e}"))

    violations: list[Violation] = []
    for mod in _passes():
        states = []
        for ctx in contexts:
            state = mod.run(ctx)
            if state is not None:
                states.append(state)
        violations.extend(mod.finalize(states))
    for ctx in contexts:
        violations.extend(ctx.violations)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, errors
