"""TPU604 — donated buffer read after the call.

``jax.jit(step, donate_argnums=(0,))`` hands the argument's device
buffer to XLA for in-place reuse: after the call the old array is
DELETED, and touching it raises (best case) or reads freed memory
through a stale alias (worst case, under the nonstandard backends the
bench notes document). The correct idiom rebinds in the same statement
(``state, metrics = step(state, batch)``). The pass is TPU104's
path-sensitive sibling:

- a call through a donated-jit callable marks each Name/attribute
  argument at a donated position,
- any READ of a marked name on any path before it is rebound reports
  (including the loop-carried shape: donated at the bottom of iteration
  N, read at the top of N+1 — the walker's double loop walk sees it),
- rebinding (any assignment target covering the name) clears the mark.

Donated callables come from three channels: a module-local
``v = jax.jit(..., donate_argnums=...)`` bind (``self._prefill``), a
jit-decorated def with donate, and — cross-file, resolved in
``finalize`` — a variable bound from a jit FACTORY (``step =
jit_train_step(...)``: the factory's return is the donated jit)."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow, jit_util
from ray_tpu._private.lint.core import FileContext, dotted_name, iter_tree


def _read_names(expr: ast.AST):
    """Dotted names READ in an expression (loads only; call receivers
    included — `state.params` reads `state`)."""
    out = []
    for node in iter_tree(expr):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.append(node.id)
        elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load):
            name = dotted_name(node)
            if name:
                out.append(name)
    return out


def _covers(read: str, donated: str) -> bool:
    """Reading `state` or `state.params` hits a donated `state`;
    reading `self` alone does not hit a donated `self.cache`."""
    return read == donated or read.startswith(donated + ".")


class _State(dataflow.PathState):
    __slots__ = ("donated",)

    def __init__(self):
        # dotted name -> (line, callable display name, resolved|callee)
        self.donated: dict[str, tuple] = {}

    def fork(self):
        st = _State()
        st.donated = dict(self.donated)
        return st

    def merge(self, other):
        # A name donated on EITHER path is unsafe at the join.
        for name, rec in other.donated.items():
            self.donated.setdefault(name, rec)


class _Walker(dataflow.FlowWalker):
    def __init__(self, ctx: FileContext, ji: jit_util.ModuleJitIndex,
                 info: dataflow.FunctionInfo, st: "_PassState"):
        self.ctx = ctx
        self.ji = ji
        self.info = info
        self.st = st
        self._reported: set[tuple] = set()

    def _scope(self):
        if self.info.class_name:
            return f"{self.info.class_name}.{self.info.node.name}"
        return self.info.node.name

    # --------------------------------------------------------- reads
    def _check_reads(self, expr, state, skip_call=None):
        if expr is None:
            return
        for node in iter_tree(expr):
            if node is skip_call:
                continue
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                self._hit(node.id, node.lineno, state)
            elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                name = dotted_name(node)
                if name:
                    self._hit(name, node.lineno, state)

    def _hit(self, read, line, state):
        for donated, (dline, cname, resolved) in state.donated.items():
            if not _covers(read, donated):
                continue
            key = (donated, line)
            if key in self._reported:
                continue
            self._reported.add(key)
            if resolved is True:
                self.ctx.report(
                    "TPU604", _node(line),
                    f"`{read}` read after `{cname}(...)` (line {dline}) "
                    f"donated `{donated}`'s buffer: donation hands the "
                    "buffer to XLA for in-place reuse — the old array "
                    "is deleted and this read raises or aliases freed "
                    "memory. Rebind the result over the argument "
                    "(`x, ... = f(x, ...)`) before any further use",
                    scope=self._scope(),
                )
            else:
                # Factory-produced callable: donation only known once
                # the program-wide factory table exists.
                self.st.events.append((
                    self.ctx, resolved, read, donated, dline, cname,
                    line, self._scope()))

    # --------------------------------------------------------- events
    def on_stmt(self, stmt, state):
        # Reads are checked on the statement's own expressions, BEFORE
        # the call marks new donations: `x2 = step(x, b)` must not
        # self-report x's use as the donating argument.
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._check_reads(stmt.value, state)
            if isinstance(stmt, ast.AugAssign):
                self._check_reads(stmt.target, state)
        elif isinstance(stmt, ast.Expr):
            self._check_reads(stmt.value, state)
        elif isinstance(stmt, (ast.Return, ast.Raise)):
            self._check_reads(getattr(stmt, "value", None)
                              or getattr(stmt, "exc", None), state)
        elif isinstance(stmt, ast.If):
            self._check_reads(stmt.test, state)
        elif isinstance(stmt, ast.While):
            self._check_reads(stmt.test, state)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_reads(stmt.iter, state)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(item.context_expr, state)
        elif isinstance(stmt, (ast.Assert, ast.Delete)):
            self._check_reads(stmt, state)

    def on_call(self, call, state):
        klass = self.info.class_name
        info = self.ji.lookup_callable(call, klass)
        cname = dotted_name(call.func)
        resolved = True
        if info is None:
            callee = self.ji.mi.resolve_call(call, klass)
            if callee is not None and callee in self.ji.jit_defs:
                info = self.ji.jit_defs[callee]
            else:
                # var bound from an unresolved-here factory call?
                if cname:
                    canon = self.ji.mi.qualify(cname, klass)
                    fac = self.st.factory_vars.get(canon)
                    if fac is not None:
                        resolved = fac  # defer to finalize
                        info = jit_util.JitInfo(line=call.lineno,
                                                donate=None)
        if info is None:
            return
        donate = info.donate
        if resolved is True and not donate:
            return
        positions = donate if resolved is True else None
        for pos, arg in enumerate(call.args):
            if positions is not None and pos not in positions:
                continue
            name = dotted_name(arg)
            if not name:
                continue
            if resolved is True:
                state.donated[name] = (call.lineno, cname, True)
            else:
                # Record the factory + position; finalize keeps the
                # event only if that position is donated there.
                state.donated[name] = (
                    call.lineno, cname, (resolved, pos))

    def on_assign(self, stmt, state):
        if isinstance(stmt, ast.AugAssign):
            return
        targets = stmt.targets if isinstance(
            stmt, ast.Assign) else [stmt.target]
        for target in targets:
            self._clear(target, state)

    def _clear(self, target, state):
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._clear(elt, state)
            return
        name = dotted_name(target)
        if not name:
            return
        for donated in list(state.donated):
            if _covers(name, donated) or _covers(donated, name):
                del state.donated[donated]


def _node(line: int):
    class N:
        lineno = line
        col_offset = 0
    return N


class _PassState:
    def __init__(self, ji: jit_util.ModuleJitIndex,
                 factory_vars: dict | None = None):
        self.ji = ji
        self.mi = ji.mi
        # canonical var -> callee qual, pruned to vars actually CALLED
        # in this module (a bound-but-never-invoked result cannot
        # donate anything).
        self.factory_vars = factory_vars or {}
        # (ctx, (factory_qual, pos), read, donated, donate_line, cname,
        #  read_line, scope) — factory events needing program context
        self.events: list[tuple] = []


def run(ctx: FileContext):
    ji = jit_util.jit_index(ctx)
    # Perf prune: a factory-bound var only matters if the VAR itself is
    # called somewhere in this module.
    src = ctx.source
    factory_vars = {
        canon: q for canon, q in ji.maybe_factory_vars.items()
        if canon.split(".")[-1] + "(" in src
    }
    # Walk only when something trackable exists: a donated local jit
    # bind/def, or a called var bound from a resolvable call (it may
    # be a cross-file jit factory — only finalize knows).
    trackable = (
        factory_vars
        or any(i.donate for i in ji.jit_vars.values())
        or any(i.donate for i in ji.jit_defs.values())
    )
    if not trackable and not ji.factories:
        return None
    st = _PassState(ji, factory_vars)
    if trackable:
        # Per-function prefilter: the flow walk only matters where a
        # tracked callable's NAME is invoked in that function's text.
        tails = {c.split(".")[-1] for c in factory_vars}
        tails |= {c.split(".")[-1] for c, i in ji.jit_vars.items()
                  if i.donate}
        tails |= {q.split(".")[-1] for q, i in ji.jit_defs.items()
                  if i.donate}
        for info in ji.mi.functions.values():
            node = info.node
            end = getattr(node, "end_lineno", len(ctx.lines))
            seg = "\n".join(ctx.lines[node.lineno - 1:end])
            if not any(t + "(" in seg for t in tails):
                continue
            walker = _Walker(ctx, ji, info, st)
            walker.walk_function(node, _State())
    return st


def finalize(states):
    states = [st for st in states if st is not None]
    if not states:
        return []
    factories: dict[str, jit_util.JitInfo] = {}
    for st in states:
        factories.update(st.ji.factories)
    if not factories:
        return []
    # Tail-name fallback: `step = jit_train_step(...)` resolves to
    # "step.jit_train_step" in the caller but the factory indexes as
    # "step.jit_train_step" only when the module tails already match —
    # unify on the bare function name too.
    by_tail = {q.split(".")[-1]: info for q, info in factories.items()}
    seen: set[tuple] = set()
    for st in states:
        for (ctx, fac_qual, read, donated, dline, cname, line,
             scope) in st.events:
            rec = None
            if isinstance(fac_qual, tuple):
                fac_qual, pos = fac_qual
            else:  # pragma: no cover - defensive
                continue
            rec = factories.get(fac_qual) or by_tail.get(
                fac_qual.split(".")[-1])
            if rec is None or not rec.donate or pos not in rec.donate:
                continue
            key = (id(ctx), line, donated)
            if key in seen:
                continue
            seen.add(key)
            ctx.report(
                "TPU604", _node(line),
                f"`{read}` read after `{cname}(...)` (line {dline}) "
                f"donated `{donated}`'s buffer (donate_argnums of the "
                f"compiled step built by `{fac_qual}`): the buffer "
                "was handed to XLA for reuse — rebind the result over "
                "the argument before any further use",
                scope=scope,
            )
    return []
