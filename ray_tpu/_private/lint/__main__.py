import sys

from ray_tpu._private.lint.cli import main

sys.exit(main())
