"""TPU101/TPU102 — collective-divergence.

Collective ops are SPMD: every rank in the group must reach the same
call in the same order or the group deadlocks until PR 1's deadline
fires. Two statically-detectable shapes:

- TPU101: a collective call nested under a rank-dependent conditional
  (``if rank == 0:``, ``if self.is_head:``) — only some ranks reach it.
- TPU102: a collective call AFTER a rank-dependent early exit
  (``if rank != 0: return`` … ``barrier()``) — some ranks left the
  function before the rendezvous.

Flow-sensitive analysis (proving both branches issue matching ops) is
a ROADMAP follow-up; symmetric patterns are pragma'd today.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name, iter_tree

COLLECTIVE_NAMES = frozenset({
    "allreduce", "allgather", "reduce", "reducescatter", "reduce_scatter",
    "broadcast", "barrier", "send", "recv", "sendrecv",
    # PR 10's async verbs dispatch the op immediately — a guarded
    # dispatch diverges exactly like a guarded sync verb.
    "allreduce_async", "reducescatter_async", "allgather_async",
    "hierarchical_allreduce",
})
# Attribute-form calls (x.barrier()) need the receiver to look like a
# collective module/group — `sock.send()` must not trip the pass.
_RECEIVER_HINTS = ("col", "collective", "comm", "group", "grp")
_RANK_TOKENS = ("rank", "is_head", "is_leader", "is_coordinator")


def _collective_modules(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(aliases of ray_tpu.collective, names imported from it)."""
    aliases: set[str] = set()
    names: set[str] = set()
    for node in iter_tree(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "collective":
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[-1] == "collective":
                for a in node.names:
                    if a.name in COLLECTIVE_NAMES:
                        names.add(a.asname or a.name)
    return aliases, names


def is_rank_dependent(test: ast.AST) -> bool:
    for node in iter_tree(test):
        name = ""
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(t in name.lower() for t in _RANK_TOKENS):
            return True
    return False


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        self._aliases, self._imported = _collective_modules(ctx.tree)
        self._guard_depth = 0
        # Per-function stack: line of the latest rank-dependent early
        # exit seen so far (None until one is found).
        self._early_exit: list[tuple[int, str] | None] = []

    # ------------------------------------------------------ helpers
    def _is_collective(self, call: ast.Call) -> str | None:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in self._imported:
                return func.id
            return None
        if isinstance(func, ast.Attribute) and func.attr in COLLECTIVE_NAMES:
            recv = dotted_name(func.value)
            last = recv.split(".")[-1].lower() if recv else ""
            if recv.split(".")[0] in self._aliases:
                return func.attr
            if any(h in last for h in _RECEIVER_HINTS):
                return func.attr
        return None

    @staticmethod
    def _branch_exits(body: list[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Break, ast.Continue, ast.Raise))

    # ------------------------------------------------------ visitors
    def enter_function(self, node):
        self._early_exit.append(None)

    def exit_function(self, node):
        self._early_exit.pop()

    def _visit_guarded(self, node):
        """Shared If/While handling: push guard depth around
        rank-dependent branches, record early exits."""
        rank_dep = is_rank_dependent(node.test)
        if rank_dep and isinstance(node, ast.If) and self._early_exit:
            for branch in (node.body, node.orelse):
                if self._branch_exits(branch):
                    self._early_exit[-1] = (
                        node.lineno,
                        type(branch[-1]).__name__.lower(),
                    )
        if rank_dep:
            self._guard_depth += 1
        self.visit(node.test)
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        if rank_dep:
            self._guard_depth -= 1

    def visit_If(self, node: ast.If):
        self._visit_guarded(node)

    def visit_While(self, node: ast.While):
        self._visit_guarded(node)

    def visit_Call(self, node: ast.Call):
        verb = self._is_collective(node)
        if verb is not None:
            if self._guard_depth > 0:
                self.ctx.report(
                    "TPU101", node,
                    f"collective op `{verb}` under a rank-dependent "
                    "conditional: ranks that skip the branch never join "
                    "the rendezvous (SPMD deadlock)",
                    scope=self.scope,
                )
            elif self._early_exit and self._early_exit[-1] is not None:
                line, kind = self._early_exit[-1]
                self.ctx.report(
                    "TPU102", node,
                    f"collective op `{verb}` after the rank-dependent "
                    f"early `{kind}` on line {line}: exited ranks never "
                    "reach this rendezvous",
                    scope=self.scope,
                )
        self.generic_visit(node)


def run(ctx: FileContext):
    # Every detectable call site names a collective verb textually —
    # attribute form at the call, from-import form on the import line
    # (even when aliased) — and both TPU101 and TPU102 additionally
    # need a rank-dependent test, whose name carries a rank token.
    if not any(name in ctx.source for name in COLLECTIVE_NAMES):
        return None
    lowered = ctx.source.lower()
    if not any(t in lowered for t in _RANK_TOKENS):
        return None
    _Visitor(ctx).visit(ctx.tree)
    return None


def finalize(states):
    return []
