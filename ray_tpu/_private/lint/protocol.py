"""Shared distributed-protocol model for the TPU70x tier.

The control plane's wire contract is all convention: an RPC method
``m`` exists iff some server class defines ``async def _on_m(self,
conn, ...)``, and ``rpc.tolerant_kwargs`` silently DROPS any request
field the handler doesn't accept (deliberate version-skew tolerance).
That tolerance is exactly why drift is invisible at runtime — a typo'd
kwarg is not an error, it's a no-op. This module extracts the handler
signature table the static passes (TPU701) and the runtime contract
sanitizer (``sanitize.check_rpc_contract``) both validate against, so
the two views can never disagree about what the contract *is*.

A "handler" here is any (async) function named ``_on_<method>`` that
takes a parameter literally named ``conn`` — the dispatch shape of
``Head._handle``/``Node._handle``/``CoreWorker._handle``
(``getattr(self, f"_on_{method}")`` called with ``conn=conn, **kw``).
Callback-style ``_on_*`` functions without a ``conn`` parameter
(``_on_head_push``, ``_on_member_dead``, ...) are not RPC handlers and
are excluded by that same test.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from ray_tpu._private.lint.core import iter_tree

#: kwargs consumed by the client transport (``Connection.call`` /
#: ``ReconnectingClient.call``) and never forwarded on the wire.
TRANSPORT_KWARGS = frozenset({"timeout", "retry"})


@dataclasses.dataclass
class HandlerSig:
    method: str
    params: set          # payload params (excluding self/conn)
    required: set        # params with no default
    varkw: bool          # handler takes **kwargs
    line: int = 0
    cls: str = ""
    path: str = ""


def _is_handler(node: ast.AST) -> bool:
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    if not node.name.startswith("_on_") or len(node.name) <= 4:
        return False
    names = {a.arg for a in node.args.args} | {
        a.arg for a in node.args.kwonlyargs}
    return "conn" in names


def handler_sig(node, cls: str = "", path: str = "") -> HandlerSig:
    """Signature model of one ``_on_<method>`` handler def."""
    args = node.args
    pos = [a.arg for a in args.args]
    n_defaults = len(args.defaults)
    required = set(pos[: len(pos) - n_defaults]) if n_defaults else set(pos)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is None:
            required.add(a.arg)
    params = set(pos) | {a.arg for a in args.kwonlyargs}
    params -= {"self", "conn"}
    required -= {"self", "conn"}
    return HandlerSig(
        method=node.name[4:],
        params=params,
        required=required,
        varkw=args.kwarg is not None,
        line=node.lineno,
        cls=cls,
        path=path,
    )


def handler_signatures(tree: ast.Module, path: str = "") -> list[HandlerSig]:
    """All RPC handler signatures defined in one module."""
    out: list[HandlerSig] = []
    for node in iter_tree(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if _is_handler(item):
                    out.append(handler_sig(item, cls=node.name, path=path))
    return out


def merge_signatures(sigs) -> dict[str, HandlerSig]:
    """Method → merged contract across every server that handles it.

    When two servers handle the same method (``get_object`` lives on
    both node and core_worker) a call site cannot know which one it
    targets, so the merged contract is the permissive union: a kwarg is
    unknown only if NO handler accepts it, a param is required only if
    EVERY handler requires it.
    """
    merged: dict[str, HandlerSig] = {}
    for sig in sigs:
        cur = merged.get(sig.method)
        if cur is None:
            merged[sig.method] = HandlerSig(
                method=sig.method, params=set(sig.params),
                required=set(sig.required), varkw=sig.varkw,
                line=sig.line, cls=sig.cls, path=sig.path)
        else:
            cur.params |= sig.params
            cur.required &= sig.required
            cur.varkw = cur.varkw or sig.varkw
    return merged


def handler_signature_table(root: str | None = None) -> dict[str, dict]:
    """Method → ``{"params", "required", "varkw"}`` for the whole
    installed ``ray_tpu`` package (or any tree rooted at ``root``).

    This is the table the runtime contract sanitizer validates
    ``Connection.call`` kwargs against — built from the same extraction
    the TPU701 static pass uses, parsed once and cached by the caller.
    Unparseable or unreadable files are skipped: a broken WIP module
    must degrade the sanitizer to fewer checks, never to a crash.
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    sigs: list[HandlerSig] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            try:
                with open(p, encoding="utf-8") as f:
                    src = f.read()
                # Textual pre-filter: every handler definition contains
                # "_on_" literally, and only ~1/6 of the package does.
                # Every spawned worker with the sanitizer armed builds
                # this table once — skipping the parse for the rest
                # keeps that first RPC cheap.
                if "_on_" not in src:
                    continue
                tree = ast.parse(src, filename=p)
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue
            sigs.extend(handler_signatures(tree, path=p))
    return {
        m: {"params": s.params, "required": s.required, "varkw": s.varkw}
        for m, s in merge_signatures(sigs).items()
    }


class FakeNode:
    """Line-only node stand-in for ``ctx.report`` at finalize time
    (protocol events outlive their AST nodes cheaply this way)."""

    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno: int, col: int = 0):
        self.lineno = lineno
        self.col_offset = col
