"""TPU703 — config knob discipline.

``config.get`` raises ``KeyError`` for an unknown name — but only when
the call actually executes, so a typo'd knob on a cold path (an error
branch, a chaos hook) survives review and detonates in production.
The inverse rot is quieter still: a knob declared in ``CONFIG_DEFS``
whose last reader was refactored away keeps its env var, README row
and test surface alive forever. Three checks:

- ``config.get("X")`` (and calls through one-hop wrappers that forward
  a parameter to ``config.get``, the ``dag/context._cfg`` idiom) must
  name a declared knob;
- raw ``os.environ`` reads of ``RAY_TPU_*`` outside ``config.py`` /
  ``test_utils.py`` bypass the override/env/default resolution order
  and are flagged (bootstrap/debugger reads carry reasoned pragmas);
- declared-but-never-read knobs report as dead at their definition
  line. "Read" is deliberately loose — ANY string mention of the knob
  name outside ``config.py`` counts — so wrapper indirection and
  docs-driven lookups don't false-positive; a knob nobody even names
  is definitively dead.

Doc-drift sub-check: when the analyzed program contains the real
``config.py``, README knob mentions (``RAY_TPU_<NAME>``) must resolve
to a declared knob or an env var the code actually touches — a renamed
knob whose README row survived reports against the README line.

Gates: unknown-key and dead-knob checks need ``CONFIG_DEFS`` in the
analyzed program; the dead check additionally needs at least one
resolved ``config.get`` site (a program with no readers loaded — e.g.
``config.py`` analyzed alone — proves nothing about deadness).
"""

from __future__ import annotations

import ast
import os
import re

from ray_tpu._private.lint import protocol
from ray_tpu._private.lint.core import FileContext, ScopeVisitor, dotted_name, iter_tree

_KNOB_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
# A quoted, fully-uppercase string literal — the textual twin of the
# old "uppercase string constant" AST walk, cheap enough to run on
# every gated file. Comments match too; that only makes the dead-knob
# rule looser, which is its design direction.
_MENTION_RE = re.compile(r"""["']([A-Z][A-Z0-9_]*)["']""")
_ENV_RE = re.compile(r"RAY_TPU_([A-Z][A-Z0-9_]*)")
_EXEMPT_FILES = ("config.py", "test_utils.py")


class _State:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.defs: dict[str, int] = {}      # knob -> def line
        self.defs_is_config = False
        self.defs_real_path = ""
        self.gets: list[tuple] = []         # (key, line, scope)
        self.mentions: set = set()          # uppercase string consts
        self.env_names: set = set()         # RAY_TPU_* touched anywhere


def _collect_defs(tree: ast.Module) -> dict[str, int]:
    defs: dict[str, int] = {}
    for node in iter_tree(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if not isinstance(value, ast.Dict):
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "CONFIG_DEFS":
                    for k in value.keys:
                        if (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            defs[k.value] = k.lineno
    return defs


def _wrapper_names(tree: ast.Module) -> set:
    """Local functions that forward a parameter to ``config.get``."""
    out: set = set()
    for node in iter_tree(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in node.args.args} | {
            a.arg for a in node.args.kwonlyargs}
        for sub in iter_tree(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "get"
                    and dotted_name(sub.func.value) == "config"
                    and len(sub.args) == 1
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id in params):
                out.add(node.name)
                break
    return out


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, st: _State, wrappers: set,
                 exempt_env: bool):
        super().__init__(ctx)
        self.st = st
        self.wrappers = wrappers
        self.exempt_env = exempt_env

    def _env_string(self, node: ast.AST) -> str | None:
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and node.value.startswith("RAY_TPU_")):
            return node.value
        return None

    def visit_Subscript(self, node: ast.Subscript):
        self.generic_visit(node)
        recv = dotted_name(node.value)
        if recv in ("os.environ", "environ"):
            env = self._env_string(node.slice)
            if env and isinstance(node.ctx, ast.Load):
                self._report_env(node, env)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        func = node.func
        if isinstance(func, ast.Name) and func.id in self.wrappers:
            if (len(node.args) == 1 and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _KNOB_RE.match(node.args[0].value)):
                self.st.gets.append(
                    (node.args[0].value, node.lineno, self.scope))
            return
        if not isinstance(func, ast.Attribute):
            return
        recv = dotted_name(func.value)
        if func.attr == "get" and recv == "config":
            if (len(node.args) == 1 and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _KNOB_RE.match(node.args[0].value)):
                self.st.gets.append(
                    (node.args[0].value, node.lineno, self.scope))
            return
        if recv in ("os.environ", "environ") and func.attr in (
                "get", "setdefault") and node.args:
            env = self._env_string(node.args[0])
            if env and func.attr == "get":
                self._report_env(node, env)
        elif recv == "os" and func.attr == "getenv" and node.args:
            env = self._env_string(node.args[0])
            if env:
                self._report_env(node, env)

    def _report_env(self, node: ast.AST, env: str) -> None:
        if self.exempt_env:
            return
        self.ctx.report(
            "TPU703", node,
            f"raw environ read of {env!r} bypasses the config registry "
            "(override -> env -> default resolution and type coercion); "
            "declare a knob in CONFIG_DEFS and use config.get()",
            scope=self.scope)


def run(ctx: FileContext):
    src = ctx.source
    interesting = ("config" in src or "RAY_TPU_" in src
                   or "CONFIG_DEFS" in src)
    if not interesting:
        return None
    st = _State(ctx)
    # Mentions and env-var names come from a regex sweep — the AST walk
    # is reserved for files that can actually contain get/env sites.
    st.mentions = {m.group(1) for m in _MENTION_RE.finditer(src)}
    st.env_names = {m.group(1) for m in _ENV_RE.finditer(src)}
    if "CONFIG_DEFS" in src:
        st.defs = _collect_defs(ctx.tree)
    if st.defs:
        st.defs_is_config = os.path.basename(
            getattr(ctx, "real_path", ctx.path)) == "config.py"
        st.defs_real_path = getattr(ctx, "real_path", ctx.path)
    has_env_read = (("environ" in src or "getenv" in src)
                    and "RAY_TPU_" in src)
    if "config.get" in src or has_env_read:
        # A one-hop wrapper's body textually contains `config.get`.
        wrappers = _wrapper_names(ctx.tree) if "config.get" in src else set()
        exempt_env = os.path.basename(
            getattr(ctx, "real_path", ctx.path)) in _EXEMPT_FILES
        _Visitor(ctx, st, wrappers, exempt_env).visit(ctx.tree)
    return st


def _find_readme(start: str) -> str | None:
    probe = os.path.dirname(os.path.abspath(start))
    for _ in range(4):
        cand = os.path.join(probe, "README.md")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None


def _doc_drift(defs: dict, env_names: set, defs_path: str) -> list:
    """README knob mentions that resolve to nothing — returned as raw
    Violations (no FileContext exists for markdown)."""
    from ray_tpu._private.lint.core import RULES, Violation

    readme = _find_readme(defs_path)
    if readme is None:
        return []
    try:
        with open(readme, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    known = set(defs) | env_names
    out, seen = [], set()
    display = os.path.relpath(readme)
    for i, line in enumerate(lines, start=1):
        for m in _ENV_RE.finditer(line):
            name = m.group(1)
            if name in known or name in seen:
                continue
            seen.add(name)
            out.append(Violation(
                rule="TPU703", name=RULES["TPU703"], path=display,
                line=i, col=0,
                message=f"README documents RAY_TPU_{name} but no such "
                        "knob exists in CONFIG_DEFS (and no code touches "
                        "that env var) — stale docs after a rename/removal",
                scope="<readme>", snippet=line.strip()))
    return out


def finalize(states):
    defs: dict[str, int] = {}
    defs_state = None
    env_names: set = set()
    mentions_outside_defs: set = set()
    n_get_sites = 0
    for st in states:
        env_names |= st.env_names
        n_get_sites += len(st.gets)
        if st.defs and defs_state is None:
            defs, defs_state = st.defs, st
    for st in states:
        if st is not defs_state:
            mentions_outside_defs |= st.mentions
        # An explicit get is definitively a read even inside the defs
        # file, and a raw env read (flagged separately) still consumes
        # the knob — neither may ALSO report it dead.
        mentions_outside_defs |= {key for key, _, _ in st.gets}
    mentions_outside_defs |= env_names
    if not defs:
        return []

    for st in states:
        for key, line, scope in st.gets:
            if key not in defs:
                st.ctx.report(
                    "TPU703", protocol.FakeNode(line),
                    f"config.get({key!r}): unknown knob — not declared in "
                    "CONFIG_DEFS; this raises KeyError when the call "
                    "executes",
                    scope=scope)

    violations = []
    if n_get_sites:
        for knob in sorted(defs):
            if knob not in mentions_outside_defs:
                defs_state.ctx.report(
                    "TPU703", protocol.FakeNode(defs[knob]),
                    f"knob {knob!r} is declared in CONFIG_DEFS but never "
                    "read anywhere in the analyzed program — dead "
                    "configuration surface",
                    scope="CONFIG_DEFS")
    if defs_state.defs_is_config:
        violations.extend(
            _doc_drift(defs, env_names, defs_state.defs_real_path))
    return violations
