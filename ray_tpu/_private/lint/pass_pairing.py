"""TPU404 — unbalanced resource pairing (path-sensitive TPU402 big
sibling).

TPU402 catches a span CM that is never *entered*. TPU404 catches the
open/close pairs the dataflow engine can follow across paths:

- **``memory.track()`` discarded**: the Registration is unreachable
  the moment it is created — nobody can ever ``close()`` it, so the
  byte claim lives (and lies) until process exit. PR 11's registry
  tolerates re-tracking by tag, but an explicitly closeable claim is
  the difference between "replaced eventually" and "retired now".
- **``memory.track()`` not closed on a path**: assigned to a local
  that reaches a ``return``/fall-off exit without ``close()`` and
  without escaping (attr/container store, return, passed on). The
  weakref leak reporter (``sanitize.watch_registration``) is the
  runtime twin for the escaped ones.
- **manual span ``__enter__`` without exception-safe ``__exit__``**:
  ``s = tracing.span(...); s.__enter__()`` must ``__exit__`` on every
  path INCLUDING the exception path — i.e. in a ``finally`` (or just
  use ``with``). An exception between enter and exit otherwise leaves
  the span open forever and every subsequent span mis-parented.

``with`` usage is always clean — the pairing is structural there."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, dotted_name, iter_tree

# Receivers that make a bare `.track(...)` the memory-ledger call.
_MEM_RECEIVERS = ("memory", "rmem", "_rmem", "mem")

_CM = "cm"               # span CM constructed, not yet entered
_OPEN = "open"           # registration created, not yet closed
_ENTERED = "entered"     # span CM manually __enter__'d
_CLOSED = "closed"
_ESCAPED = "escaped"
_RANK = {_CM: 0, _CLOSED: 0, _OPEN: 1, _ENTERED: 1, _ESCAPED: 2}


def _track_call(node: ast.AST, imported_track: bool) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "track" and imported_track
    if isinstance(func, ast.Attribute) and func.attr == "track":
        recv = dotted_name(func.value)
        last = recv.split(".")[-1].lower() if recv else ""
        return any(last == h or last.endswith(h) for h in _MEM_RECEIVERS)
    return False


class _State(dataflow.PathState):
    __slots__ = ("vars",)

    def __init__(self):
        # name -> (status, open_line, kind, risky)
        # risky: a call happened while the resource was open — the
        # exception-path flag for manual __enter__.
        self.vars: dict[str, tuple] = {}

    def fork(self):
        st = _State()
        st.vars = dict(self.vars)
        return st

    def merge(self, other):
        for name, rec in other.vars.items():
            mine = self.vars.get(name)
            if mine is None or _RANK[rec[0]] > _RANK[mine[0]]:
                self.vars[name] = rec


class _Walker(dataflow.FlowWalker):
    def __init__(self, ctx: FileContext, scope: str, imported_track: bool,
                 fn_node=None):
        self.ctx = ctx
        self.scope = scope
        self.imported_track = imported_track
        self._reported: set[tuple] = set()
        # `global X; X = memory.track(...)` escapes — module-lifetime
        # claims are closed by whoever replaces them.
        self._globals: set[str] = set()
        if fn_node is not None:
            for n in iter_tree(fn_node):
                if isinstance(n, (ast.Global, ast.Nonlocal)):
                    self._globals.update(n.names)
        # names whose __exit__/close happened outside any finally while
        # calls could raise in between — flagged once per function
        self.unsafe_exits: dict[str, tuple] = {}

    def _report(self, key, line, message):
        if key in self._reported:
            return
        self._reported.add(key)
        self.ctx.report("TPU404", _node(line), message, scope=self.scope)

    # ----------------------------------------------------------- events
    def on_stmt(self, stmt, state):
        if isinstance(stmt, ast.Expr) and _track_call(
                stmt.value, self.imported_track):
            self._report(
                ("discard", stmt.value.lineno),
                stmt.value.lineno,
                "`memory.track(...)` result discarded: the "
                "Registration can never be `close()`d — the byte "
                "claim outlives its subsystem and the ledger lies; "
                "keep the handle (and close it) or use `with`",
            )

    def on_assign(self, stmt, state):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            self._escape_names(getattr(stmt, "value", None), state)
            return
        target = stmt.targets[0]
        if isinstance(target, ast.Name):
            if target.id in self._globals:
                self._escape_names(stmt.value, state)
                return
            if _track_call(stmt.value, self.imported_track):
                state.vars[target.id] = (_OPEN, stmt.lineno,
                                         "registration", False)
                return
            from ray_tpu._private.lint.pass_metrics import _span_cm
            if isinstance(stmt.value, ast.Call) and _span_cm(
                    stmt.value) is not None:
                state.vars[target.id] = (_CM, stmt.lineno, "span",
                                         False)
                return
            if isinstance(stmt.value, ast.Name):
                src = state.vars.pop(stmt.value.id, None)
                if src is not None:
                    state.vars[target.id] = src
                    return
            state.vars.pop(target.id, None)
            return
        self._escape_names(stmt.value, state)

    def on_call(self, call, state):
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            name = func.value.id
            rec = state.vars.get(name)
            if rec is not None:
                if func.attr in ("close", "__exit__"):
                    state.vars[name] = (_CLOSED, rec[1], rec[2], rec[3])
                    if (rec[0] == _ENTERED and not self.in_finally
                            and rec[3]):
                        self.unsafe_exits.setdefault(
                            name, (rec[1], call.lineno))
                    return
                if func.attr == "__enter__":
                    # anchor at the enter, not the construction
                    state.vars[name] = (_ENTERED, call.lineno, rec[2],
                                        rec[3])
                    return
                if func.attr == "update" or func.attr == "add":
                    return
        # any other call while a resource is open can raise: mark risky
        for name, rec in list(state.vars.items()):
            if rec[0] in (_OPEN, _ENTERED) and not rec[3]:
                state.vars[name] = (rec[0], rec[1], rec[2], True)
        # resources passed onward escape
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            self._escape_names(arg, state)

    def on_with(self, item, state, is_async):
        # `with reg:` / `with memory.track(...) as reg:` is the clean
        # structural pairing; an as-bound name is managed.
        if item.optional_vars is not None and isinstance(
                item.optional_vars, ast.Name):
            state.vars.pop(item.optional_vars.id, None)
        if isinstance(item.context_expr, ast.Name):
            name = item.context_expr.id
            rec = state.vars.get(name)
            if rec is not None:
                state.vars[name] = (_CLOSED, rec[1], rec[2], rec[3])
        return None

    def on_exit(self, state, node, kind):
        if kind == "return":
            self._escape_names(getattr(node, "value", None), state)
        if kind in ("raise", "break", "continue"):
            return
        for name, (status, line, res_kind, risky) in state.vars.items():
            if status == _OPEN:
                self._report(
                    ("leak", line, name),
                    line,
                    f"`{name} = memory.track(...)` registration is "
                    "neither `close()`d nor stored on a path reaching "
                    "function exit: the byte claim leaks and the "
                    "device-memory ledger over-reports until process "
                    "exit",
                )
            elif status == _ENTERED:
                self._report(
                    ("enter-leak", line, name),
                    line,
                    f"`{name}.__enter__()` has no matching "
                    "`__exit__` on a path reaching function exit: the "
                    "span never closes and every later span "
                    "mis-parents — pair it in a `finally` or use "
                    "`with`",
                )

    def finish(self):
        for name, (open_line, close_line) in self.unsafe_exits.items():
            self._report(
                ("unsafe", open_line, name),
                open_line,
                f"`{name}.__enter__()` is `__exit__`ed only on the "
                f"happy path (line {close_line}, not in a `finally`): "
                "any exception raised in between leaves the span open "
                "— move the `__exit__` into a `finally` or use `with`",
            )

    # ---------------------------------------------------------- helpers
    def _escape_names(self, expr, state):
        if expr is None:
            return
        for n in iter_tree(expr):
            if isinstance(n, ast.Name) and n.id in state.vars:
                rec = state.vars[n.id]
                state.vars[n.id] = (_ESCAPED, rec[1], rec[2], rec[3])


def _node(line: int):
    class N:
        lineno = line
        col_offset = 0
    return N


def run(ctx: FileContext):
    src = ctx.source
    if "track" not in src and "__enter__" not in src:
        return None
    imported_track = False
    for node in ctx.nodes:
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[-1] == "memory":
                for a in node.names:
                    if a.name == "track":
                        imported_track = True
    mi = dataflow.index(ctx)
    for info in mi.functions.values():
        scope = (f"{info.class_name}.{info.node.name}"
                 if info.class_name else info.node.name)
        walker = _Walker(ctx, scope, imported_track, info.node)
        walker.walk_function(info.node, _State())
        walker.finish()
    return None


def finalize(states):
    return []
