"""tpulint: ray_tpu-specific static analysis.

Five AST passes grounded in this codebase's real failure classes (the
bug shapes PRs 1-3 spent ~3k LoC defending against at runtime):

- ``collective-divergence`` (TPU101/TPU102): collective ops under
  rank-dependent control flow — the SPMD deadlock shape.
- ``lock-discipline`` (TPU201/TPU202): blocking calls while a
  ``threading.Lock`` with-block is open, plus cross-function
  lock-order cycles.
- ``broad-except`` (TPU301): ``except Exception``/bare ``except``
  that neither re-raises, logs, nor carries an allow pragma.
- ``metric-hygiene`` (TPU401/TPU402): metric constructors inside
  functions (re-registration churn) and span APIs used without a
  context manager.
- ``rpc-reentrancy`` (TPU501): RPC handlers that call back into an
  RPC handled by their own process (self-deadlock).

Violations are suppressed line-by-line with::

    # tpulint: allow(<rule> reason=<why this is deliberate>)

and pre-existing debt is pinned in ``lint_baseline.json`` — only NEW
violations fail CI (``ray_tpu lint --baseline lint_baseline.json``).
"""

from ray_tpu._private.lint.core import (  # noqa: F401
    Violation,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from ray_tpu._private.lint.baseline import (  # noqa: F401
    diff_against_baseline,
    load_baseline,
    make_baseline,
)
