"""tpulint: ray_tpu-specific static analysis.

Twenty passes grounded in this codebase's real failure classes (the
bug shapes PRs 1-11 spent thousands of LoC defending against at
runtime), the flow-sensitive ones built on the v2 interprocedural
dataflow engine (``dataflow.py``: module symbol tables + call graph +
alias sets + a branch/loop/early-return-aware abstract interpreter):

- ``collective-divergence`` (TPU101/TPU102): collective ops under
  rank-dependent control flow — the SPMD deadlock shape.
- ``rank-divergence-flow`` (TPU103): the same hazard hidden behind
  helper calls, tracked through the call graph and per-path guards.
- ``dropped-handle`` (TPU104): ``*_async()`` CollectiveWork handles
  discarded, never ``wait()``ed on a path, or overwritten pending.
- ``lock-discipline`` (TPU201/TPU202): blocking calls while a
  ``threading.Lock`` with-block is open, plus cross-function
  lock-order cycles.
- ``async-lock`` (TPU203): threading locks held across ``await``,
  blocking calls inside ``asyncio.Lock`` sections, unbalanced manual
  acquires in ``async def``.
- ``lock-alias`` (TPU204): locks passed as arguments / stored in
  attributes or containers joining the TPU202 order graph.
- ``broad-except`` (TPU301): ``except Exception``/bare ``except``
  that neither re-raises, logs, nor carries an allow pragma.
- ``metric-hygiene`` (TPU401/TPU402/TPU403): metric constructors in
  functions, span CMs never entered, unbounded metric labels.
- ``resource-pairing`` (TPU404): ``memory.track()`` registrations
  never closed, span ``__enter__`` without exception-safe
  ``__exit__`` — checked path-sensitively.
- ``rpc-reentrancy`` (TPU501): RPC handlers that call back into an
  RPC handled by their own process (self-deadlock).
- ``host-sync-in-hot-path`` (TPU601): ``block_until_ready`` /
  ``device_get`` / ``.item()`` (and, in compute-phase spans,
  ``float()``/``np.asarray()``) reached — transitively — from a step
  loop or compute span; the PR-10 ``wait()`` tail join is exempt.
- ``jit-side-effect`` (TPU602): metrics/logging/span emission/
  closure-append inside a jit-traced body — runs once at trace time
  and silently lies thereafter.
- ``recompilation-hazard`` (TPU603): loop-varying scalars or
  data-dependent shapes fed to a jitted callee, unhashable
  ``static_argnums`` values.
- ``donation-misuse`` (TPU604): an argument named in
  ``donate_argnums`` read on any path after the donating call.
- ``jit-boundary-divergence`` (TPU605): a rank-/slice-dependent
  branch selecting WHICH compiled program runs — the in-program
  collective deadlock TPU103 cannot see.
- ``rpc-contract-drift`` (TPU701): every ``*.call("m", **kw)`` site
  bound cross-file to its ``async def _on_m(self, conn, ...)``
  handler — unknown methods, kwargs ``tolerant_kwargs`` would silently
  drop, required params never passed, positional payloads that become
  the transport ``timeout``.
- ``journal-replay-completeness`` (TPU702): every ``(table, op)``
  written via ``_journal_append`` needs a replay branch in
  ``_restore_from_journal`` and a snapshot field; replayed payload
  keys must be a subset of the keys every append writes.
- ``knob-discipline`` (TPU703): ``config.get`` keys absent from
  ``CONFIG_DEFS``, raw ``RAY_TPU_*`` env reads outside the config
  layer, dead declared-but-never-read knobs, README doc drift.
- ``pubsub-channel-discipline`` (TPU704): publishes nobody hears,
  subscriptions to never-published channels, push handlers blind to
  the coalesced ``{"batch": [...]}`` frame shape.
- ``metric-schema-drift`` (TPU705): one metric name registered with
  differing label sets or types across modules.

The TPU60x rules have runtime twins in ``ray_tpu/_private/sanitize.py``
(the jit compile watch and the host-sync tracer, ``RAY_TPU_SANITIZE=1``);
TPU701 has one too (``sanitize.check_rpc_contract``, armed in
``Connection.call`` by the same switch — the runtime backstop for the
dynamic-method and ``**kw``-splat sites the static pass must skip).

Violations are suppressed line-by-line with::

    # tpulint: allow(<rule> reason=<why this is deliberate>)

The tree is clean — there is no checked-in baseline anymore — but the
baseline plumbing (``--baseline``/``--update-baseline``) remains for
third-party trees adopting the linter with existing debt. Use
``ray_tpu lint --changed`` on the pre-commit path: it lints only the
files in ``git diff`` plus their call-graph neighbors.
"""

from ray_tpu._private.lint.core import (  # noqa: F401
    Violation,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from ray_tpu._private.lint.baseline import (  # noqa: F401
    diff_against_baseline,
    load_baseline,
    make_baseline,
)
