"""TPU203 — async-lock discipline.

Three shapes where locks and the event loop interact badly:

- **threading lock across ``await``**: a ``with self._lock:`` block in
  an ``async def`` that awaits inside the critical section. The
  coroutine suspends holding an OS lock; every *thread* that wants the
  lock stalls for an arbitrary number of scheduler turns — and if one
  of those threads is the loop's own executor, the loop deadlocks.
  (Moved here from TPU201: the fix is different — switch to
  ``asyncio.Lock`` or shrink the section — so it gets its own id.)
- **blocking call inside an ``asyncio.Lock`` section**: ``async with
  self._lock:`` around ``time.sleep`` / subprocess / blocking RPC
  freezes the whole event loop while every other coroutine queues on
  the lock — the single-threaded twin of TPU201.
- **unbalanced manual acquire in ``async def``**: ``await
  lk.acquire()`` (or ``lk.acquire()``) where some return path skips
  ``release()``. With coroutines, the "other path" is usually an early
  return after an awaited call raised — the lock stays held forever
  because no stack unwind releases it. Use ``async with`` (flagged
  clean), or release in a ``finally``.

Lock detection is name-based like TPU201/202 (``lock``/``mutex`` in
the last name component); ``async with`` implies an asyncio lock,
plain ``with`` implies a threading lock."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, iter_tree
from ray_tpu._private.lint.pass_locks import _lock_expr_name


class _State(dataflow.PathState):
    __slots__ = ("held_sync", "held_async", "manual")

    def __init__(self):
        self.held_sync: tuple = ()     # threading locks via `with`
        self.held_async: tuple = ()    # asyncio locks via `async with`
        self.manual: dict[str, int] = {}   # lock name -> acquire line

    def fork(self):
        st = _State()
        st.held_sync = self.held_sync
        st.held_async = self.held_async
        st.manual = dict(self.manual)
        return st

    def merge(self, other):
        # A lock held on EITHER joining path is held on the join: the
        # imbalance check fires at exits, where "held on some path" is
        # exactly the bug.
        for name, line in other.manual.items():
            self.manual.setdefault(name, line)


class _Walker(dataflow.FlowWalker):
    def __init__(self, ctx: FileContext, scope: str, fn_node,
                 blocking_reason):
        self.ctx = ctx
        self.scope = scope
        self.is_async = True
        self._blocking_reason = blocking_reason
        self._reported: set[tuple] = set()
        self.releases: set[str] = set()   # lock names released anywhere
        # Calls that are the direct operand of `await` are loop-friendly
        # by construction — `await client.call(...)` must not read as a
        # blocking RPC.
        self._awaited: set[int] = set()
        for node in iter_tree(fn_node):
            if isinstance(node, ast.Await) and isinstance(
                    node.value, ast.Call):
                self._awaited.add(id(node.value))

    def _report(self, key, line, message):
        if key in self._reported:
            return
        self._reported.add(key)
        self.ctx.report("TPU203", _node(line), message, scope=self.scope)

    # ------------------------------------------------------------ with
    def on_with(self, item, state, is_async):
        name = _lock_expr_name(item.context_expr)
        if name is None:
            return None
        if is_async:
            state.held_async = state.held_async + (name,)
            return ("async", name)
        state.held_sync = state.held_sync + (name,)
        return ("sync", name)

    def on_with_exit(self, token, state):
        if token is None:
            return
        kind, name = token
        if kind == "async" and state.held_async:
            state.held_async = state.held_async[:-1]
        elif kind == "sync" and state.held_sync:
            state.held_sync = state.held_sync[:-1]

    # ----------------------------------------------------------- events
    def on_await(self, node, state):
        if state.held_sync:
            self._report(
                ("await", node.lineno),
                node.lineno,
                f"`await` while holding threading lock "
                f"`{state.held_sync[-1]}`: the coroutine suspends with "
                "an OS lock held — every thread needing it stalls for "
                "arbitrarily many scheduler turns; use asyncio.Lock "
                "or move the await outside the section",
            )

    def on_call(self, call, state):
        func = call.func
        if isinstance(func, ast.Attribute):
            name = _lock_expr_name(func.value)
            if name is not None and self.is_async:
                if func.attr == "acquire":
                    state.manual.setdefault(name, call.lineno)
                elif func.attr == "release":
                    state.manual.pop(name, None)
                    self.releases.add(name)
        if state.held_async and id(call) not in self._awaited:
            reason = self._blocking_reason(call)
            if reason is not None:
                self._report(
                    ("block", call.lineno),
                    call.lineno,
                    f"{reason} inside asyncio lock section "
                    f"`{state.held_async[-1]}`: the event loop freezes "
                    "while every coroutine queued on the lock waits — "
                    "await an executor instead",
                )

    def on_exit(self, state, node, kind):
        if kind not in ("return", "fall"):
            return
        for name, line in state.manual.items():
            if name in self.releases:
                self._report(
                    ("imbalance", line, name),
                    line,
                    f"`{name}.acquire()` here is released on another "
                    "path but not on the one reaching line "
                    f"{getattr(node, 'lineno', line)}: the lock stays "
                    "held forever on this path — release in a "
                    "`finally` or use `async with`",
                )
            else:
                self._report(
                    ("never-released", line, name),
                    line,
                    f"`{name}.acquire()` in async def is never "
                    "released: no stack unwind frees a manually "
                    "acquired lock — use `async with` or release in "
                    "a `finally`",
                )


def _node(line: int):
    class N:
        lineno = line
        col_offset = 0
    return N


def run(ctx: FileContext):
    src = ctx.source
    if "async" not in src:
        return None
    from ray_tpu._private.lint.pass_locks import _Visitor as _LockVisitor

    # Borrow TPU201's blocking-call classifier without re-instantiating
    # its full state machine.
    classifier = _LockVisitor(ctx)
    mi = dataflow.index(ctx)
    for info in mi.functions.values():
        # Every TPU203 shape needs `await`/`async with`/a coroutine
        # acquire — all exclusive to async defs; skip the rest.
        if not info.is_async:
            continue
        scope = (f"{info.class_name}.{info.node.name}"
                 if info.class_name else info.node.name)
        walker = _Walker(ctx, scope, info.node,
                         classifier._blocking_reason)
        walker.walk_function(info.node, _State())
    return None


def finalize(states):
    return []
