"""TPU705 — metric schema drift across modules.

The metrics registry raises at runtime when the same metric name is
re-registered with a different type or label set — but only when both
registrations happen to execute in the same process. Two modules that
never co-import (a trainer counter and a serve counter sharing a
name) drift forever, and the scrape endpoint exports whichever loaded
first. This pass is the static twin of that runtime raise: it
collects every metric constructor with a constant name
(``Counter/Gauge/Histogram("name", ..., tag_keys=(...))``, the same
detection shape as TPU401) across the whole analyzed program and
reports every site whose type or label set disagrees with the first
registration of that name.

Dynamic names or tag tuples are out of static reach and skipped.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint import protocol
from ray_tpu._private.lint.core import FileContext, ScopeVisitor
from ray_tpu._private.lint.pass_metrics import _metric_ctor


class _Site:
    __slots__ = ("ctx", "line", "name", "ctor", "tags", "scope")

    def __init__(self, ctx, line, name, ctor, tags, scope):
        self.ctx = ctx
        self.line = line
        self.name = name
        self.ctor = ctor
        self.tags = tags  # frozenset of label names, or None (dynamic)
        self.scope = scope


def _tag_keys(call: ast.Call):
    """frozenset of constant tag keys; empty when omitted; None when
    the tuple is dynamic."""
    for kw in call.keywords:
        if kw.arg != "tag_keys":
            continue
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            keys = []
            for el in kw.value.elts:
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, str)):
                    keys.append(el.value)
                else:
                    return None
            return frozenset(keys)
        return None
    return frozenset()


class _State:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.sites: list[_Site] = []


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext, st: _State):
        super().__init__(ctx)
        self.st = st

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        ctor = _metric_ctor(node)
        if ctor is None or not node.args:
            return
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            return
        self.st.sites.append(_Site(
            self.ctx, node.lineno, name.value, ctor, _tag_keys(node),
            self.scope))


def run(ctx: FileContext):
    if not any(c in ctx.source for c in ("Counter", "Gauge", "Histogram")):
        return None
    st = _State(ctx)
    _Visitor(ctx, st).visit(ctx.tree)
    if not st.sites:
        return None
    return st


def finalize(states):
    first: dict[str, _Site] = {}
    ordered = [s for st in states for s in st.sites]
    for site in ordered:
        ref = first.setdefault(site.name, site)
        if ref is site:
            continue
        where = f"{ref.ctx.path}:{ref.line}"
        if site.ctor != ref.ctor:
            site.ctx.report(
                "TPU705", protocol.FakeNode(site.line),
                f"metric {site.name!r} registered as {site.ctor} here but "
                f"as {ref.ctor} at {where} — the registry raises if both "
                "modules ever co-import, and exports whichever loaded "
                "first otherwise",
                scope=site.scope)
        elif (site.tags is not None and ref.tags is not None
                and site.tags != ref.tags):
            site.ctx.report(
                "TPU705", protocol.FakeNode(site.line),
                f"metric {site.name!r} registered with labels "
                f"{sorted(site.tags)} here but {sorted(ref.tags)} at "
                f"{where} — series from the two sites are "
                "unjoinable and the runtime registry raises on "
                "co-import",
                scope=site.scope)
    return []
