"""TPU501 — rpc-reentrancy.

Head/node RPC handlers follow the ``_on_<method>`` naming convention
(dispatched by ``_handle``). A handler that calls
``<peer>.call("<method>")`` where ``<method>`` is handled by the SAME
module is calling back into its own process: under load (or when the
connection pool serializes on one peer) the inner call queues behind
the very handler issuing it — a self-deadlock that only manifests as
an RPC deadline. Restructure to call the local method directly
(``self._on_x(...)`` / shared helper) instead of going over the wire.
"""

from __future__ import annotations

import ast

from ray_tpu._private.lint.core import FileContext, ScopeVisitor, iter_tree


def _handler_names(tree: ast.Module) -> set[str]:
    out = set()
    for node in iter_tree(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("_on_"):
                out.add(node.name[len("_on_"):])
    return out


class _Visitor(ScopeVisitor):
    def __init__(self, ctx: FileContext):
        super().__init__(ctx)
        self._handlers = _handler_names(ctx.tree)

    def visit_Call(self, node: ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "call"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and self._func
            and any(f.startswith("_on_") for f in self._func)
        ):
            method = node.args[0].value
            if method in self._handlers:
                self.ctx.report(
                    "TPU501", node,
                    f"RPC handler issues `call(\"{method}\")` — a "
                    "method handled by THIS module: the round-trip "
                    "back into our own server can queue behind this "
                    "very handler (self-deadlock); call the local "
                    f"method `_on_{method}` directly",
                    scope=self.scope,
                )
        self.generic_visit(node)


def run(ctx: FileContext):
    # Reentrancy needs BOTH a local `_on_<method>` handler and a
    # `.call(` site in the same file.
    if "_on_" not in ctx.source or ".call(" not in ctx.source:
        return None
    _Visitor(ctx).visit(ctx.tree)
    return None


def finalize(states):
    return []
