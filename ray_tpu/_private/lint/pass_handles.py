"""TPU104 — dropped collective handle.

PR 10's async verbs (``allreduce_async`` and friends,
``BucketStream.sync_async``) return a :class:`CollectiveWork` whose op
is already in flight. A handle that is never ``wait()``ed is a
*silently lost collective*: the op completes (or faults) and nobody
observes the result or the typed error — the overlap analogue of a
swallowed exception. Three path-sensitive shapes:

- **discarded**: ``g.allreduce_async(t)`` as a bare expression
  statement — the handle is unreachable the moment it is created.
- **never waited**: assigned to a local that reaches a ``return``/
  fall-off exit with no ``wait()`` on that path.
- **overwritten while pending**: the variable is re-bound to a new
  ``*_async`` handle (including by the next loop iteration) while the
  previous handle was never waited.

Escapes are forgiven: a handle that is returned, passed to a call,
or stored into an attribute/container is some other code's to join —
the runtime leak reporter (``sanitize.watch_work``) is the dynamic
backstop there. ``raise`` exits are also forgiven: abandoning in-flight
work on the error path is the documented destroy semantics."""

from __future__ import annotations

import ast

from ray_tpu._private.lint import dataflow
from ray_tpu._private.lint.core import FileContext, iter_tree

ASYNC_VERBS = frozenset({
    "allreduce_async", "reducescatter_async", "allgather_async",
    "sync_async",
})

_PENDING = "pending"
_WAITED = "waited"
_ESCAPED = "escaped"
_RANKS = {_WAITED: 0, _PENDING: 1, _ESCAPED: 2}


def _handle_call(node: ast.AST) -> str | None:
    """The async verb name when ``node`` is directly a handle-creating
    call, else None."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name) and func.id in ASYNC_VERBS:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in ASYNC_VERBS:
        return func.attr
    return None


class _State(dataflow.PathState):
    __slots__ = ("vars",)

    def __init__(self):
        # name -> (status, open_line, verb)
        self.vars: dict[str, tuple] = {}

    def fork(self):
        st = _State()
        st.vars = dict(self.vars)
        return st

    def merge(self, other):
        for name, rec in other.vars.items():
            mine = self.vars.get(name)
            if mine is None or _RANKS[rec[0]] > _RANKS[mine[0]]:
                self.vars[name] = rec


class _Walker(dataflow.FlowWalker):
    def __init__(self, ctx: FileContext, scope: str, fn_node=None):
        self.ctx = ctx
        self.scope = scope
        self._reported: set[tuple] = set()
        # `global X; X = ..._async()` escapes: module state outlives
        # this function's paths.
        self._globals: set[str] = set()
        if fn_node is not None:
            for n in iter_tree(fn_node):
                if isinstance(n, (ast.Global, ast.Nonlocal)):
                    self._globals.update(n.names)

    # --------------------------------------------------------- reporting
    def _report(self, key, line, message):
        if key in self._reported:
            return
        self._reported.add(key)
        self.ctx.report(
            "TPU104", _node(line), message, scope=self.scope)

    # ----------------------------------------------------------- events
    def on_stmt(self, stmt, state):
        if isinstance(stmt, ast.Expr):
            verb = _handle_call(stmt.value)
            if verb is not None:
                self._report(
                    ("discard", stmt.value.lineno),
                    stmt.value.lineno,
                    f"result of `{verb}(...)` discarded: the op is in "
                    "flight but its handle is unreachable — the result "
                    "(and any typed fault) is silently dropped; "
                    "`wait()` it or keep the handle",
                )

    def on_assign(self, stmt, state):
        if not isinstance(stmt, ast.Assign):
            return
        targets = stmt.targets
        if len(targets) != 1:
            self._escape_names(stmt.value, state)
            return
        target = targets[0]
        verb = _handle_call(stmt.value)
        if isinstance(target, ast.Name):
            if target.id in self._globals:
                self._escape_names(stmt.value, state)
                return
            prev = state.vars.get(target.id)
            if prev is not None and prev[0] == _PENDING:
                self._report(
                    ("overwrite", stmt.lineno, target.id),
                    stmt.lineno,
                    f"`{target.id}` rebound while its previous "
                    f"`{prev[2]}` handle (line {prev[1]}) is still "
                    "pending: the in-flight op's result is silently "
                    "dropped — wait() the old handle first (or collect "
                    "handles in a list)",
                )
            if verb is not None:
                state.vars[target.id] = (_PENDING, stmt.lineno, verb)
                return
            if isinstance(stmt.value, ast.Name):
                # alias move: g = h transfers ownership
                src = state.vars.pop(stmt.value.id, None)
                if src is not None:
                    state.vars[target.id] = src
                    return
            state.vars.pop(target.id, None)
            return
        # attribute / subscript / tuple target: whatever names feed the
        # RHS escape, and a directly-created handle escapes too.
        self._escape_names(stmt.value, state)

    def on_call(self, call, state):
        func = call.func
        # h.wait() / h.wait(timeout_s=...) marks the handle joined.
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            name = func.value.id
            if name in state.vars:
                if func.attr == "wait":
                    rec = state.vars[name]
                    state.vars[name] = (_WAITED, rec[1], rec[2])
                return
        # A pending handle passed as an argument escapes (anywhere in
        # the argument expression — lists of handles included).
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            self._escape_names(arg, state)

    def on_exit(self, state, node, kind):
        if kind == "return":
            ret = getattr(node, "value", None)
            if ret is not None:
                self._escape_names(ret, state)
        if kind in ("raise", "break", "continue"):
            return
        for name, (status, line, verb) in state.vars.items():
            if status == _PENDING:
                self._report(
                    ("unwaited", line, name),
                    line,
                    f"`{name} = {verb}(...)` handle is never "
                    "`wait()`ed on a path reaching function exit: the "
                    "dispatched collective's result and typed errors "
                    "are lost (SPMD peers may be left joining an op "
                    "nobody observes)",
                )

    # ---------------------------------------------------------- helpers
    def _escape_names(self, expr, state):
        if expr is None:
            return
        for n in iter_tree(expr):
            if isinstance(n, ast.Name) and n.id in state.vars:
                rec = state.vars[n.id]
                state.vars[n.id] = (_ESCAPED, rec[1], rec[2])


def _node(line: int):
    class N:
        lineno = line
        col_offset = 0
    return N


def run(ctx: FileContext):
    if "_async" not in ctx.source:
        return None
    mi = dataflow.index(ctx)
    for info in mi.functions.values():
        scope = (f"{info.class_name}.{info.node.name}"
                 if info.class_name else info.node.name)
        walker = _Walker(ctx, scope, info.node)
        walker.walk_function(info.node, _State())
    return None


def finalize(states):
    return []
