"""Version shims for jax API moves.

The repo targets the current jax surface (``jax.shard_map``); older
jaxlibs (<= 0.4.x, what some images bake) still ship it as
``jax.experimental.shard_map.shard_map``. Import from here so every
shard_map user (collective backends, parallel layers, pallas wrappers)
resolves the right symbol once instead of nine modules guessing.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax <= 0.4.x: experimental home, and the
    # replication-check kwarg is still called check_rep there.
    import functools

    from jax.experimental.shard_map import (  # type: ignore
        shard_map as _experimental_shard_map,
    )

    @functools.wraps(_experimental_shard_map)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, *args, **kwargs)


__all__ = ["shard_map"]
