"""TPU accelerator manager (reference:
python/ray/_private/accelerators/tpu.py:18–66 — TPU_VISIBLE_CHIPS, GKE
env vars, devfs chip files; topology env vars become labels the way
util/tpu.py slice scheduling expects)."""

from __future__ import annotations

import glob
import os

from ray_tpu._private.accelerators.accelerator import AcceleratorManager


class TPUAcceleratorManager(AcceleratorManager):
    def resource_name(self) -> str:
        return "TPU"

    def detect_count(self) -> int:
        from ray_tpu._private import config

        fake = config.get("FAKE_CHIPS")
        if fake != "":  # "0" is a valid fake (simulate a chipless host)
            return int(fake)
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible is None:
            visible = os.environ.get("TPU_VISIBLE_DEVICES")
        if visible is not None:
            # "" means explicitly zero visible chips.
            return len([c for c in visible.split(",") if c])
        try:
            chips = glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
            chips = [c for c in chips if c != "/dev/vfio/vfio"]
            if chips:
                return len(chips)
        except OSError:
            pass
        # The axon tunnel exposes one chip without devfs entries; report
        # it from the env marker only (never by initializing a backend).
        if "axon" in os.environ.get("JAX_PLATFORMS", ""):
            return 1
        return 0

    def detect_labels(self) -> dict[str, str]:
        labels: dict[str, str] = {}
        for var, label in (
            ("TPU_ACCELERATOR_TYPE", "ray_tpu.io/accelerator-type"),
            ("TPU_WORKER_ID", "ray_tpu.io/tpu-worker-id"),
            ("TPU_NAME", "ray_tpu.io/tpu-slice-name"),
        ):
            val = os.environ.get(var)
            if val:
                labels[label] = val
        return labels
