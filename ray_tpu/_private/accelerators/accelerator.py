"""AcceleratorManager ABC (reference:
python/ray/_private/accelerators/accelerator.py:18 — per-vendor
detection, visibility envs, and labels behind one interface)."""

from __future__ import annotations


class AcceleratorManager:
    """One per vendor. Detection must be PASSIVE (env vars, devfs) —
    never initialize a device runtime in the node daemon (grabbing the
    chip there would starve the processes that need it)."""

    def resource_name(self) -> str:
        """Scheduler resource name, e.g. "TPU"."""
        raise NotImplementedError

    def detect_count(self) -> int:
        """Number of visible accelerators on this host (0 when absent)."""
        raise NotImplementedError

    def detect_labels(self) -> dict[str, str]:
        """Topology labels for the node (slice name, worker id, ...)."""
        return {}
