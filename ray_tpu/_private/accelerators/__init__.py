"""Accelerator plugin registry (reference:
python/ray/_private/accelerators/ — `AcceleratorManager` ABC
accelerator.py:18 with per-vendor managers; get_all_accelerator_managers
drives node resource detection)."""

from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

_MANAGERS: list[AcceleratorManager] = [TPUAcceleratorManager()]


def register(manager: AcceleratorManager) -> None:
    """Add a vendor manager (user plugins for non-TPU accelerators)."""
    _MANAGERS.append(manager)


def all_managers() -> list[AcceleratorManager]:
    return list(_MANAGERS)


def detect_accelerator_resources() -> dict[str, float]:
    """{resource_name: count} across every registered manager."""
    out: dict[str, float] = {}
    for mgr in _MANAGERS:
        n = mgr.detect_count()
        if n:
            out[mgr.resource_name()] = float(n)
    return out


def detect_accelerator_labels() -> dict[str, str]:
    out: dict[str, str] = {}
    for mgr in _MANAGERS:
        out.update(mgr.detect_labels())
    return out


__all__ = [
    "AcceleratorManager",
    "TPUAcceleratorManager",
    "register",
    "all_managers",
    "detect_accelerator_resources",
    "detect_accelerator_labels",
]
