"""Asyncio framed-message RPC with request multiplexing and server push.

Fills the role of the reference's gRPC wrapper layer (reference:
src/ray/rpc/grpc_server.h:86, retryable client retryable_grpc_client.h)
for the Python control plane. Wire format (reference: protobuf-defined
messages, src/ray/protobuf/gcs_service.proto / common.proto — typed and
versioned so peers can skew):

    [u32 length][u8 wire-version][msgpack array (kind, req_id, payload)]

with kind ∈ {REQ, RESP, ERR, PUSH}. Control frames are STRICT msgpack —
plain data only (str/bytes/numbers/lists/dicts); anything else is an
encode-time TypeError, so the deserializer never executes code on
behalf of a peer. User payloads (task args, objects, function blobs)
ride INSIDE frames as opaque bytes fields, (cloud)pickled at a higher
layer and unpickled only by their owner. A frame whose version byte
doesn't match is rejected with a clean error before any parsing —
that's the rolling-upgrade / version-skew contract. One persistent
connection per peer pair; calls multiplex on req_id; PUSH frames
deliver server-initiated messages (pubsub). A chaos hook mirrors the
reference's rpc_chaos.h fault injection for protocol tests.
"""

from __future__ import annotations

import asyncio
import os
import random
import struct
from typing import Any, Awaitable, Callable

import msgpack

import logging

logger = logging.getLogger("ray_tpu.rpc")

REQ, RESP, ERR, PUSH = 0, 1, 2, 3
WIRE_VERSION = 1
_HDR = struct.Struct("<I")
_MAX_FRAME = 1 << 31
_AUTH_MAGIC = b"RTPUAUTH"
_AUTH_MAX = 4096


def _msgpack_default(obj):
    """Encode-time escape hatch for buffer views only; every other
    type is a hard error — the control plane is typed data, never
    pickled objects."""
    if isinstance(obj, memoryview):
        return bytes(obj)
    if isinstance(obj, bytearray):
        return bytes(obj)
    raise TypeError(
        f"control-plane frames carry plain data only; got "
        f"{type(obj).__name__} — pickle it into a bytes field at the "
        f"call site if it is user payload"
    )


_KEY_TYPES = (str, int, bytes, bool, float, type(None))


def _check_map_keys(obj) -> None:
    """msgpack happily PACKS a tuple-keyed dict (array key) but the
    receiver's decode then dies with an unhashable-type error — a
    silent remote poison. Enforce scalar keys at encode time, where
    the bug is."""
    if isinstance(obj, dict):
        for k, v in obj.items():
            if not isinstance(k, _KEY_TYPES):
                raise TypeError(
                    f"control-plane map keys must be scalars, got "
                    f"{type(k).__name__} key {k!r}"
                )
            _check_map_keys(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _check_map_keys(v)


def pack_frame(frame) -> bytes:
    _check_map_keys(frame)
    return msgpack.packb(
        frame, use_bin_type=True, default=_msgpack_default
    )


def unpack_frame(data) -> Any:
    return msgpack.unpackb(
        data, raw=False, strict_map_key=False, use_list=True
    )


_sig_cache: dict = {}


def tolerant_kwargs(fn, kw: dict) -> dict:
    """Drop request fields the handler doesn't declare (the
    unknown-field tolerance half of version skew: a NEWER peer's extra
    fields are ignored by an older server, like unknown protobuf
    fields). Handlers taking **kwargs receive everything."""
    import inspect

    target = getattr(fn, "__func__", fn)
    cached = _sig_cache.get(target)
    if cached is None:
        sig = inspect.signature(target)
        has_var = any(
            p.kind == p.VAR_KEYWORD for p in sig.parameters.values()
        )
        cached = (has_var, frozenset(sig.parameters))
        _sig_cache[target] = cached
    has_var, allowed = cached
    if has_var:
        return kw
    return {k: v for k, v in kw.items() if k in allowed}


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def _chaos_drop(method: str) -> bool:
    """Chaos injection: RAY_TPU_RPC_FAILURE="m1:p1,m2:p2,…" drops
    matching requests before send (reference: rpc_chaos.h:24,
    RAY_testing_rpc_failure ray_config_def.h:850). Read per-call so
    tests can flip it at runtime; method="*" matches everything. A
    comma-separated spec targets several RPC types in one run (the
    collective-abort tests drop op and rendezvous traffic together)."""
    from ray_tpu._private import config

    chaos = config.get("RPC_FAILURE")
    if not chaos:
        return False
    for spec in chaos.split(","):
        spec = spec.strip()
        if not spec:
            continue
        # rpartition: method names may themselves contain colons
        # (extension handlers like "col_op:<group>").
        name, _, prob = spec.rpartition(":")
        if name != "*" and method != name:
            continue
        try:
            p = float(prob or 0)
        except ValueError:
            continue
        if random.random() < p:
            return True
    return False


def _auth_token() -> str:
    from ray_tpu._private import config

    return config.get("AUTH_TOKEN")


def _ssl_server_ctx():
    """Server TLS context when TLS_CERT/TLS_KEY are configured (the
    token handshake then rides an encrypted channel; reference pairs
    its token validator with gRPC TLS)."""
    import ssl

    from ray_tpu._private import config

    cert, key = config.get("TLS_CERT"), config.get("TLS_KEY")
    if not cert or not key:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    return ctx


_client_ctx_cache: dict = {}


def _ssl_client_ctx():
    """Client TLS context pinning the cluster cert: any server holding
    the matching key is trusted, hostname is irrelevant. Cached per
    cert path — connect() sits on the hot transfer path."""
    import ssl

    from ray_tpu._private import config

    cert = config.get("TLS_CERT")
    if not cert:
        return None
    ctx = _client_ctx_cache.get(cert)
    if ctx is None:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cert)
        _client_ctx_cache[cert] = ctx
    return ctx


async def _read_frame(reader: asyncio.StreamReader) -> tuple:
    hdr = await reader.readexactly(_HDR.size)
    (length,) = _HDR.unpack(hdr)
    if length > min(_MAX_FRAME, _max_frame()):
        raise RpcError(f"oversized frame: {length}")
    if length < 1:
        raise RpcError("empty frame")
    data = await reader.readexactly(length)
    version = data[0]
    if version != WIRE_VERSION:
        # Version skew (e.g. a peer running an older release whose
        # frames were pickled, first byte 0x80): refuse cleanly, never
        # feed the bytes to a parser that wasn't written for them.
        raise RpcError(
            f"unsupported wire version {version} (this process speaks "
            f"v{WIRE_VERSION}; upgrade or downgrade the peer)"
        )
    # memoryview: never copy a multi-MiB chunk just to strip 1 byte.
    return unpack_frame(memoryview(data)[1:])


def _max_frame() -> int:
    from ray_tpu._private import config

    return config.get("RPC_MAX_FRAME")


async def _server_auth(reader: asyncio.StreamReader, token: str) -> bool:
    """Pre-auth handshake check. The ONLY bytes a stranger can make the
    server parse are this fixed-size frame, compared constant-time — no
    pickle touches unauthenticated input (reference: token auth
    rpc/authentication/authentication_token_validator.h:26)."""
    import hmac

    try:
        hdr = await reader.readexactly(_HDR.size)
        (length,) = _HDR.unpack(hdr)
        if length > _AUTH_MAX:
            return False
        data = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return False
    return hmac.compare_digest(data, _AUTH_MAGIC + token.encode())


# Large-transfer tuning: the asyncio stream default (64 KiB reader
# limit, ~208 KiB kernel socket buffers) makes a 5 MiB object chunk
# cost dozens of event-loop wakeups and transport-buffer memmoves
# (~14 ms/chunk measured). A multi-MiB reader limit + socket buffers
# let one chunk move in a few syscalls (reference: plasma/object
# manager move chunks over dedicated high-watermark gRPC streams).
_STREAM_LIMIT = 16 * 1024 * 1024
_SOCK_BUF = 8 * 1024 * 1024


def _tune_socket(sock) -> None:
    import socket as _socket

    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, _SOCK_BUF)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, _SOCK_BUF)
    except OSError:
        pass


def _write_frame(writer: asyncio.StreamWriter, frame: tuple) -> None:
    data = pack_frame(frame)
    writer.write(_HDR.pack(len(data) + 1) + bytes([WIRE_VERSION]))
    # Separate write: concatenating header+payload would copy the whole
    # multi-MiB payload just to prepend 5 bytes.
    writer.write(data)


Handler = Callable[[str, dict, "Connection"], Awaitable[Any]]


class Connection:
    """One live peer connection, usable from both server and client side."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Handler | None = None,
        on_push: Callable[[Any], None] | None = None,
        on_close: Callable[["Connection"], None] | None = None,
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.on_push = on_push
        self.on_close = on_close
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._task = asyncio.ensure_future(self._recv_loop())
        # Server handlers can stash per-connection state (e.g. subscriber
        # registration) here.
        self.state: dict[str, Any] = {}

    @property
    def peer(self) -> str:
        try:
            host, port = self.writer.get_extra_info("peername")[:2]
            return f"{host}:{port}"
        # tpulint: allow(broad-except reason=peername is unavailable on a closing transport; this is a display label, never control flow)
        except Exception:
            return "?"

    async def call(self, method: str, timeout: float | None = None, **kw):
        # Failures raised BEFORE the request hits the wire carry
        # sent=False: callers holding side resources (e.g. a worker
        # lease) know the peer never saw the request and can safely
        # reuse them (reference: rpc_chaos distinguishes request vs
        # response failures for idempotence testing).
        if self._closed:
            err = ConnectionLost(f"connection to {self.peer} closed")
            err.sent = False
            raise err
        if _chaos_drop(method):
            err = ConnectionLost(f"chaos: dropped {method}")
            err.sent = False
            raise err
        from ray_tpu._private import sanitize
        if sanitize.enabled():
            # Runtime twin of TPU701: surface contract misses the
            # static pass can't resolve (dynamic method names,
            # kwargs-dict splats) before tolerant_kwargs eats them.
            sanitize.check_rpc_contract(method, kw)
        self._next_id += 1
        req_id = self._next_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            try:
                _write_frame(self.writer, (REQ, req_id, (method, kw)))
                await self.writer.drain()
            except OSError as e:
                # A transport torn down between the recv loop noticing
                # and this send surfaces as a raw ConnectionResetError
                # from drain(); reconnecting callers key on
                # ConnectionLost, and a raw OSError would skip their
                # retry loop. sent stays unknowable (default True):
                # bytes queued before the loss may have been delivered.
                raise ConnectionLost(
                    f"connection to {self.peer} lost mid-call: {e}"
                ) from e
            return await asyncio.wait_for(fut, timeout)
        finally:
            # Covers encode failures too (strict msgpack raising on a
            # bad kwarg must not leak the pending entry forever).
            self._pending.pop(req_id, None)

    def push(self, payload: Any) -> None:
        if not self._closed:
            _write_frame(self.writer, (PUSH, 0, payload))

    async def _recv_loop(self):
        try:
            while True:
                kind, req_id, payload = await _read_frame(self.reader)
                if kind == REQ:
                    asyncio.ensure_future(self._serve(req_id, payload))
                elif kind == RESP:
                    fut = self._pending.get(req_id)
                    if fut and not fut.done():
                        fut.set_result(payload)
                elif kind == ERR:
                    fut = self._pending.get(req_id)
                    if fut and not fut.done():
                        fut.set_exception(RpcError(payload))
                elif kind == PUSH and self.on_push:
                    self.on_push(payload)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.CancelledError,
        ):
            pass
        except RpcError as e:
            # Version skew / malformed frame: say WHY before dropping
            # the peer, or the operator only ever sees ConnectionLost.
            logger.warning(
                "dropping connection to %s: %s", self.peer, e
            )
        except Exception:  # noqa: BLE001 - decode bugs must be visible
            logger.exception(
                "dropping connection to %s: frame decode failed", self.peer
            )
        finally:
            self._shutdown()

    async def _serve(self, req_id: int, payload):
        method, kw = payload
        try:
            if self.handler is None:
                raise RpcError("connection has no handler")
            result = await self.handler(method, kw, self)
            _write_frame(self.writer, (RESP, req_id, result))
        # tpulint: allow(broad-except reason=the handler error IS propagated — serialized into the ERR frame the caller raises from)
        except Exception as e:  # noqa: BLE001 - errors travel to the caller
            try:
                _write_frame(self.writer, (ERR, req_id, f"{type(e).__name__}: {e}"))
            except Exception:
                logger.debug(
                    "could not deliver error reply to %s (conn closing)",
                    self.peer,
                )
        try:
            await self.writer.drain()
        # tpulint: allow(broad-except reason=drain on a dying transport; the recv loop reports the drop with its cause)
        except Exception:
            pass

    def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection lost"))
        self._pending.clear()
        try:
            self.writer.close()
        # tpulint: allow(broad-except reason=closing an already-broken transport during teardown; every caller-visible failure was already delivered via the pending futures)
        except Exception:
            pass
        if self.on_close:
            self.on_close(self)

    async def close(self):
        self._task.cancel()
        self._shutdown()


class Server:
    """TCP server dispatching REQ frames to an async handler."""

    def __init__(self, handler: Handler):
        self.handler = handler
        self.connections: set[Connection] = set()
        self._server: asyncio.AbstractServer | None = None
        self.port: int | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        async def on_conn(reader, writer):
            token = _auth_token()
            if token:
                try:
                    ok = await asyncio.wait_for(
                        _server_auth(reader, token), timeout=5.0
                    )
                except (asyncio.TimeoutError, Exception):  # noqa: BLE001
                    logger.warning(
                        "auth handshake failed; refusing connection"
                    )
                    ok = False
                if not ok:
                    # Refuse before any frame dispatch: an
                    # unauthenticated peer never reaches the pickle
                    # layer (deserialization = code execution).
                    try:
                        writer.close()
                    # tpulint: allow(broad-except reason=refusing an unauthenticated peer; the socket may already be gone and there is nothing to tell it)
                    except Exception:  # noqa: BLE001
                        pass
                    return
            conn = Connection(
                reader,
                writer,
                handler=self.handler,
                on_close=self.connections.discard,
            )
            self.connections.add(conn)

        self._server = await asyncio.start_server(
            on_conn, host, port, limit=_STREAM_LIMIT, ssl=_ssl_server_ctx()
        )
        for sock in self._server.sockets:
            _tune_socket(sock)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        if self._server:
            self._server.close()
        # Close accepted connections BEFORE wait_closed: since 3.12,
        # wait_closed blocks until every connection the server is
        # handling finishes — a live peer (e.g. the head's conn to a
        # stopping node) would hang shutdown forever.
        for conn in list(self.connections):
            await conn.close()
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass


async def connect(
    addr: str,
    handler: Handler | None = None,
    on_push: Callable[[Any], None] | None = None,
    retries: int = 3,
    retry_delay: float = 0.2,
) -> Connection:
    """Dial ``host:port`` with simple connection retry (reference:
    retryable_grpc_client.h)."""
    host, _, port = addr.rpartition(":")
    last: Exception | None = None
    token = _auth_token()
    for attempt in range(retries):
        try:
            reader, writer = await asyncio.open_connection(
                host, int(port), limit=_STREAM_LIMIT, ssl=_ssl_client_ctx()
            )
            sock = writer.get_extra_info("socket")
            if sock is not None:
                _tune_socket(sock)
            if token:
                blob = _AUTH_MAGIC + token.encode()
                writer.write(_HDR.pack(len(blob)) + blob)
                await writer.drain()
            return Connection(reader, writer, handler=handler, on_push=on_push)
        except (OSError, asyncio.TimeoutError) as e:
            # OSError covers the whole dial-failure family (refused,
            # ETIMEDOUT, EHOSTUNREACH, gaierror) — all must surface as
            # ConnectionLost so retry loops keyed on RpcError survive
            # transient outages.
            last = e
            # Jittered: a herd of clients dialing a restarted peer must
            # not re-knock in lockstep.
            await asyncio.sleep(
                retry_delay * (2**attempt) * (0.5 + random.random())
            )
    err = ConnectionLost(f"cannot connect to {addr}: {last}")
    # A failed dial provably never put the request on the wire: let
    # at-most-once callers (retry=False) safely re-send later.
    err.sent = False
    raise err


def backoff_delay(
    attempt: int,
    base: float | None = None,
    cap: float | None = None,
    rng: "random.Random | None" = None,
) -> float:
    """Full-jitter exponential backoff: uniform(0, min(cap, base*2^n)).

    The jitter is the point, not a refinement: after a head restart
    every node, driver, and replica re-dials through
    ReconnectingClient at once, and a deterministic schedule (the old
    fixed 0.3s) re-knocks in lockstep — a thundering herd that can
    re-crash the head exactly when it is replaying its journal. A
    uniform draw over the whole window spreads the herd across it.
    """
    from ray_tpu._private import config

    if base is None:
        base = config.get("RPC_BACKOFF_BASE_S")
    if cap is None:
        cap = config.get("RPC_BACKOFF_MAX_S")
    # 2**min(n, 16) keeps the ceiling finite for pathological attempt
    # counts; the cap dominates long before that.
    ceiling = min(float(cap), float(base) * (2 ** min(max(attempt, 0), 16)))
    if ceiling <= 0:
        return 0.0
    return (rng or random).uniform(0.0, ceiling)


class ReconnectingClient:
    """Client endpoint that survives peer restarts: re-dials on
    connection loss and retries the in-flight call until a deadline
    (reference: RetryableGrpcClient retryable_grpc_client.h +
    NotifyGCSRestart-driven resubscription, node_manager.proto:325).

    Callers must only route IDEMPOTENT methods through this (a call whose
    response was lost is re-sent); `on_reconnect(conn)` runs after each
    successful re-dial — with the RAW new Connection, since the client's
    own call() is locked during the dial — so owners can re-register /
    resubscribe state the restarted peer lost.
    """

    def __init__(
        self,
        addr: str,
        on_push: Callable[[Any], None] | None = None,
        on_reconnect: Callable[[Connection], Awaitable[None]] | None = None,
        reconnect_timeout: float = 20.0,
    ):
        self.addr = addr
        self.on_push = on_push
        self.on_reconnect = on_reconnect
        self.reconnect_timeout = reconnect_timeout
        self._conn: Connection | None = None
        self._lock: asyncio.Lock | None = None
        self._closed = False

    async def connect(self) -> "ReconnectingClient":
        # Instrumented under RAY_TPU_SANITIZE=1 (TPU203's runtime
        # twin): the proxy/serve control plane reconnects through this
        # lock, so inversions against replica/model locks surface.
        from ray_tpu._private.sanitize import maybe_async_lock

        self._lock = maybe_async_lock(f"rpc.client.{self.addr}")
        self._conn = await connect(self.addr, on_push=self.on_push)
        return self

    async def _ensure(self) -> Connection:
        if self._closed:
            err = ConnectionLost(f"client to {self.addr} closed")
            err.sent = False
            raise err
        conn = self._conn
        if conn is not None and not conn._closed:
            return conn
        async with self._lock:
            if self._conn is not None and not self._conn._closed:
                return self._conn
            self._conn = await connect(
                self.addr, on_push=self.on_push, retries=5
            )
            if self.on_reconnect is not None:
                await self.on_reconnect(self._conn)
            return self._conn

    async def call(
        self,
        method: str,
        timeout: float | None = None,
        retry: bool = True,
        **kw,
    ):
        """``retry=False`` marks a NON-idempotent call (kv_put with
        overwrite=False, log publish): it still rides reconnects for
        requests that provably never reached the wire (sent=False), but
        a call whose response was lost is NOT re-sent — the peer may
        already have executed it (at-most-once instead of
        at-least-once)."""
        import time as _time

        from ray_tpu._private import config

        deadline = _time.monotonic() + self.reconnect_timeout
        attempts = 0
        while True:
            try:
                conn = await self._ensure()
                return await conn.call(method, timeout=timeout, **kw)
            except ConnectionLost as e:
                sent = getattr(e, "sent", True)
                if self._closed or _time.monotonic() >= deadline:
                    raise
                if not retry and sent:
                    raise
                # Chaos-dropped requests (sent=False on a live conn)
                # propagate: retrying them here would defeat the fault
                # injection the chaos hook exists for.
                if sent is False and not (
                    self._conn is None or self._conn._closed
                ):
                    raise
                attempts += 1
                max_attempts = config.get("RPC_RECONNECT_ATTEMPTS")
                if max_attempts and attempts >= max_attempts:
                    raise
                # Jittered exponential backoff (not the old fixed
                # 0.3s): a cluster-wide reconnect herd after a head
                # restart spreads instead of spiking — see
                # backoff_delay.
                await asyncio.sleep(backoff_delay(attempts - 1))

    async def close(self):
        self._closed = True
        if self._conn is not None:
            await self._conn.close()
