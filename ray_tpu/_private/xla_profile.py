"""XLA program analysis: HLO cost walking and xplane trace parsing.

The two halves of the compiled-program profiler (train/profile.py):

**Static** — :func:`analyze_hlo_text` walks the post-optimization HLO
module text of a compiled executable and buckets every instruction into
the profiler's category taxonomy (matmul / collective /
elementwise_fusion / layout), accumulating analytic FLOPs and HBM bytes
per bucket. ``compiled.cost_analysis()`` alone is NOT enough: XLA's
aggregate counts each ``while`` body ONCE, so a layer scan of L
transformer blocks under-reports matmul FLOPs by ~L×. The walker
recurses through called computations and multiplies a while body's cost
by its trip count (parsed from the ``compare(..., constant(N))`` in the
condition region).

**Empirical** — :func:`parse_xplane` reads the ``*.xplane.pb`` files the
jax/XLA profiler writes. The shipped ``tensorboard_plugin_profile``
wheel exposes no ``xplane_pb2`` module, so this is a minimal pure-Python
protobuf wire parser over the handful of field numbers the profiler
needs (XSpace.planes=1; XPlane name=2/lines=3/event_metadata=4; XLine
name=2/events=4; XEvent metadata_id=1/duration_ps=3; XEventMetadata
id=1/name=2). :func:`measured_category_seconds` then sums leaf HLO-op
event durations per category, skipping infrastructure wrappers
(ThunkExecutor, profiler spans) and control-flow shells (``while``/
``call``/``conditional``) whose children appear as their own events.

Everything here is backend-agnostic text/bytes processing — no device
access, importable on any host.
"""

from __future__ import annotations

import re

CATEGORIES = ("matmul", "collective", "elementwise_fusion", "layout")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVE_OPS = {
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
    "all-reduce-start", "all-gather-start", "collective-permute-start",
}
# *-done halves of async collective pairs: the cost was charged at the
# start op; counting both would double every async collective.
_COLLECTIVE_DONE_OPS = {
    "all-reduce-done", "all-gather-done", "collective-permute-done",
}
_MATMUL_OPS = {"dot", "convolution"}
_LAYOUT_OPS = {
    "copy", "copy-start", "transpose", "reshape", "slice",
    "dynamic-slice", "dynamic-update-slice", "concatenate", "pad",
    "gather", "scatter", "broadcast", "reverse", "iota",
}
# Zero-cost bookkeeping: no bytes move, no math runs.
_FREE_OPS = {
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "copy-done", "partition-id", "replica-id", "domain",
    "opt-barrier", "custom-call-done",
}
_CONTROL_OPS = {"while", "call", "conditional", "fusion", "async-start"}

_SHAPE_RE = re.compile(
    r"(pred|[suf]\d+|bf16|f8\w*|c64|c128)\[([0-9,]*)\](?:\{[^}]*\})?"
)
# `%name = <shape> opcode(` — the shape is a single token or a
# parenthesized tuple (one nesting level is enough for real modules).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"([a-z][a-z0-9\-]*)\((.*)$"
)
_COMP_HEADER_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s+->\s+.*\{\s*$"
)
_DIMS_ATTR_RE = {
    key: re.compile(key + r"=\{([0-9,]*)\}")
    for key in (
        "lhs_contracting_dims", "rhs_contracting_dims",
        "lhs_batch_dims", "rhs_batch_dims",
    )
}
_CALLED_RE = re.compile(r"(condition|body|calls)=%?([\w.\-]+)")
_CONST_RE = re.compile(r"constant\((\d+)\)")
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_CUSTOM_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')


def shape_bytes(token: str) -> int:
    """Total bytes of one shape token (``f32[2,128]{1,0}`` → 1024);
    tuples sum their members; unparseable tokens cost 0."""
    total = 0
    for m in _SHAPE_RE.finditer(token):
        total += _dtype_dims_bytes(m.group(1), m.group(2))
    return total


def _dtype_dims_bytes(dtype: str, dims_str: str) -> int:
    n = 1
    for d in dims_str.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _shape_dims(token: str) -> list[int]:
    m = _SHAPE_RE.search(token)
    if m is None:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def _dot_flops(operands: str, attrs: str) -> float:
    """2·batch·M·K·N from the dot's operand shapes and dimension
    numbers (the first two shape tokens in the operand list are lhs and
    rhs)."""
    shapes = _SHAPE_RE.findall(operands)
    if len(shapes) < 2:
        return 0.0
    lhs = [int(d) for d in shapes[0][1].split(",") if d]
    rhs = [int(d) for d in shapes[1][1].split(",") if d]

    def dims_of(key: str) -> set[int]:
        m = _DIMS_ATTR_RE[key].search(attrs)
        if m is None:
            return set()
        return {int(d) for d in m.group(1).split(",") if d}

    lc, lb = dims_of("lhs_contracting_dims"), dims_of("lhs_batch_dims")
    rc, rb = dims_of("rhs_contracting_dims"), dims_of("rhs_batch_dims")
    batch = 1
    for i in lb:
        if i < len(lhs):
            batch *= lhs[i]
    m_size = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m_size *= d
    k_size = 1
    for i in lc:
        if i < len(lhs):
            k_size *= lhs[i]
    n_size = 1
    for i, d in enumerate(rhs):
        if i not in rc and i not in rb:
            n_size *= d
    return 2.0 * batch * m_size * k_size * n_size


def categorize_opcode(opcode: str) -> str | None:
    """Category of a plain (non-fusion, non-control) opcode; None for
    free bookkeeping ops."""
    if opcode in _FREE_OPS:
        return None
    if opcode in _MATMUL_OPS:
        return "matmul"
    if opcode in _COLLECTIVE_OPS:
        return "collective"
    if opcode in _COLLECTIVE_DONE_OPS:
        return None
    if opcode in _LAYOUT_OPS:
        return "layout"
    # Everything else that touches data is elementwise-ish (add,
    # multiply, reduce, select, compare, convert, exp, rsqrt, ...).
    return "elementwise_fusion"


_EVENT_SKIP_PREFIXES = ("$", "(")
_EVENT_CONTROL = {"while", "call", "conditional", "tuple", "async"}


def categorize_event_name(name: str) -> str | None:
    """Category of one xplane event by its HLO instruction name
    (``dot.6``, ``broadcast_add_fusion``, ``while.808``). None = not a
    leaf HLO op (infrastructure wrapper or control-flow shell whose
    children are their own events) — uncounted."""
    if "::" in name or name.startswith(_EVENT_SKIP_PREFIXES):
        return None  # ThunkExecutor::Execute, $profiler.py spans, ...
    base = name.split(".")[0]
    if base in _EVENT_CONTROL:
        return None
    if "fusion" in name:
        if re.search(r"\b(dot|conv|matmul|gemm)", name):
            return "matmul"
        return "elementwise_fusion"
    return categorize_opcode(base)


# ------------------------------------------------------ HLO walking
class _Instr:
    __slots__ = ("name", "opcode", "out_bytes", "operands", "attrs")

    def __init__(self, name, opcode, out_bytes, operands, attrs):
        self.name = name
        self.opcode = opcode
        self.out_bytes = out_bytes
        self.operands = operands
        self.attrs = attrs


def _parse_computations(text: str) -> tuple[dict, str]:
    """HLO text → {computation name: [instructions]}, entry name."""
    comps: dict[str, list[_Instr]] = {}
    entry = ""
    current: list[_Instr] | None = None
    for line in text.splitlines():
        if current is None:
            m = _COMP_HEADER_RE.match(line)
            if m is not None:
                current = comps[m.group(2)] = []
                if m.group(1):
                    entry = m.group(2)
            continue
        if line.startswith("}"):
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name, shape, opcode, rest = m.groups()
        # Split the remainder at the operand-closing paren: dimension
        # attributes follow it. Splitting on "), " is robust enough —
        # shapes/attrs inside the operand list contain no "), ".
        cut = rest.find("), ")
        operands = rest[:cut] if cut >= 0 else rest.rstrip(")")
        attrs = rest[cut + 3:] if cut >= 0 else ""
        current.append(
            _Instr(name, opcode, shape_bytes(shape), operands, attrs)
        )
    return comps, entry


def _trip_count(comp: list[_Instr]) -> int:
    """Trip count of a while loop from its condition computation: the
    induction variable compares against ``constant(N)``. LT/GT bound N
    trips; LE/GE one more. Unparseable conditions cost 1 (never 0 —
    undercounting is the failure mode this exists to fix)."""
    bound = None
    direction = "LT"
    for ins in comp:
        if ins.opcode == "constant":
            # The instruction regex consumes "constant(" as the
            # opcode, leaving the literal as the bare operand.
            m = re.match(r"\s*(\d+)\s*$", ins.operands)
            if m is not None:
                bound = max(bound or 0, int(m.group(1)))
            continue
        if ins.opcode == "compare":
            m = re.search(r"direction=(\w+)", ins.attrs)
            if m is None:
                m = re.search(r"direction=(\w+)", ins.operands)
            if m is not None:
                direction = m.group(1)
        m = _CONST_RE.search(ins.operands) or _CONST_RE.search(ins.attrs)
        if m is not None:
            bound = max(bound or 0, int(m.group(1)))
    if bound is None:
        return 1
    return bound + 1 if direction in ("LE", "GE") else max(1, bound)


def _has_matmul(comp: list[_Instr]) -> bool:
    return any(i.opcode in _MATMUL_OPS for i in comp)


def _group_size(attrs: str) -> int | None:
    m = _REPLICA_GROUPS_RE.search(attrs)
    if m is None:
        return None
    return len(m.group(1).split(","))


def analyze_hlo_text(text: str) -> dict:
    """Walk an optimized HLO module and price every instruction into
    the category taxonomy.

    Returns ``{"categories": {cat: {"flops", "bytes", "ops"}},
    "collective_ops": [{"op", "bytes", "group"}],
    "while_trips": {name: trip}}``. Bytes are the HBM traffic proxy
    (operands + output at each instruction/fusion boundary); fusion
    internals cost nothing HBM-wise, but a dot inside a fused
    computation is still charged its FLOPs under matmul.
    """
    comps, entry = _parse_computations(text)
    cats = {c: {"flops": 0.0, "bytes": 0.0, "ops": 0} for c in CATEGORIES}
    collective_ops: list[dict] = []
    while_trips: dict[str, int] = {}

    def charge(cat: str, flops: float, nbytes: float) -> None:
        cats[cat]["flops"] += flops
        cats[cat]["bytes"] += nbytes
        cats[cat]["ops"] += 1

    def fused_dot_flops(comp_name: str, mult: float) -> float:
        total = 0.0
        for ins in comps.get(comp_name, ()):
            if ins.opcode == "dot":
                total += _dot_flops(ins.operands, ins.attrs) * mult
        return total

    def walk(comp_name: str, mult: float, stack: tuple) -> None:
        if comp_name in stack:  # defensive: HLO call graphs are acyclic
            return
        stack = stack + (comp_name,)
        for ins in comps.get(comp_name, ()):
            boundary = ins.out_bytes + shape_bytes(ins.operands)
            if ins.opcode == "while":
                called = dict(
                    (k, v) for k, v in _CALLED_RE.findall(ins.attrs)
                )
                cond = called.get("condition")
                body = called.get("body")
                trips = _trip_count(comps.get(cond, [])) if cond else 1
                while_trips[ins.name] = trips
                if body:
                    walk(body, mult * trips, stack)
                if cond:
                    walk(cond, mult * trips, stack)
            elif ins.opcode in ("call", "conditional", "async-start"):
                for _kind, target in _CALLED_RE.findall(ins.attrs):
                    walk(target, mult, stack)
            elif ins.opcode == "fusion":
                called = [t for _k, t in _CALLED_RE.findall(ins.attrs)]
                target = called[0] if called else None
                if target and _has_matmul(comps.get(target, [])):
                    charge(
                        "matmul",
                        fused_dot_flops(target, mult),
                        boundary * mult,
                    )
                else:
                    charge("elementwise_fusion", 0.0, boundary * mult)
            elif ins.opcode == "custom-call":
                m = _CUSTOM_TARGET_RE.search(ins.attrs) or (
                    _CUSTOM_TARGET_RE.search(ins.operands)
                )
                target = (m.group(1) if m else "").lower()
                if re.search(r"dot|matmul|gemm|conv", target):
                    charge("matmul", 0.0, boundary * mult)
                elif re.search(r"all.?reduce|all.?gather|all.?to.?all|"
                               r"reduce.?scatter|collective", target):
                    charge("collective", 0.0, boundary * mult)
                    collective_ops.append({
                        "op": target, "bytes": boundary * mult,
                        "group": _group_size(ins.attrs),
                    })
                else:
                    charge("elementwise_fusion", 0.0, boundary * mult)
            elif ins.opcode in _COLLECTIVE_OPS:
                charge("collective", 0.0, ins.out_bytes * mult)
                collective_ops.append({
                    "op": ins.opcode.replace("-start", ""),
                    "bytes": ins.out_bytes * mult,
                    "group": _group_size(ins.attrs),
                })
            elif ins.opcode == "dot":
                charge(
                    "matmul",
                    _dot_flops(ins.operands, ins.attrs) * mult,
                    boundary * mult,
                )
            elif ins.opcode == "convolution":
                # No convs in the flagship; charge bytes, skip flops.
                charge("matmul", 0.0, boundary * mult)
            else:
                cat = categorize_opcode(ins.opcode)
                if cat is not None:
                    charge(cat, 0.0, boundary * mult)

    if entry:
        walk(entry, 1.0, ())
    return {
        "categories": cats,
        "collective_ops": collective_ops,
        "while_trips": while_trips,
    }


# --------------------------------------------------- xplane parsing
def _varint(buf: bytes, i: int) -> tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf: bytes):
    """(field_number, wire_type, value) triples of one message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        wt = key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield key >> 3, wt, v


def parse_xplane(data: bytes) -> list[dict]:
    """XSpace bytes → [{"plane", "line", "name", "dur_ps", "count"}]
    aggregated per (plane, line, event name)."""
    out: dict[tuple, list] = {}
    for fnum, wt, plane in _fields(data):
        if fnum != 1 or wt != 2:
            continue
        pname = ""
        lines = []
        meta: dict[int, str] = {}
        for f2, w2, v2 in _fields(plane):
            if f2 == 2 and w2 == 2:
                pname = v2.decode("utf-8", "replace")
            elif f2 == 3 and w2 == 2:
                lines.append(v2)
            elif f2 == 4 and w2 == 2:
                entry = None
                for f3, _w3, v3 in _fields(v2):
                    if f3 == 2:
                        entry = v3
                if entry is None:
                    continue
                mid = None
                mname = ""
                for f4, _w4, v4 in _fields(entry):
                    if f4 == 1:
                        mid = v4
                    elif f4 == 2:
                        mname = v4.decode("utf-8", "replace")
                if mid is not None:
                    meta[mid] = mname
        for raw in lines:
            lname = ""
            events = []
            for f2, w2, v2 in _fields(raw):
                if f2 == 2 and w2 == 2:
                    lname = v2.decode("utf-8", "replace")
                elif f2 == 4 and w2 == 2:
                    events.append(v2)
            for ev in events:
                mid = None
                dur = 0
                for f3, _w3, v3 in _fields(ev):
                    if f3 == 1:
                        mid = v3
                    elif f3 == 3:
                        dur = v3
                key = (pname, lname, meta.get(mid, "?"))
                rec = out.setdefault(key, [0, 0])
                rec[0] += dur
                rec[1] += 1
    return [
        {"plane": p, "line": ln, "name": nm, "dur_ps": d, "count": c}
        for (p, ln, nm), (d, c) in out.items()
    ]


def _is_device_line(plane: str, line: str) -> bool:
    """Lines carrying device op execution: TPU device planes entirely;
    on the CPU backend, the host plane's ``tf_XLA*`` executor lines."""
    if "/device:" in plane:
        return True
    return line.startswith("tf_XLA")


def measured_category_seconds(data: bytes) -> dict:
    """One capture's per-category measured seconds: sum of leaf HLO op
    event durations on device lines. ``device_busy_s`` is the same sum
    including uncategorizable leaf ops. On a multi-threaded CPU backend
    concurrent leaf ops on one executor line can sum past wall clock —
    the attribution layer normalizes against the step wall."""
    cats = {c: 0.0 for c in CATEGORIES}
    busy = 0.0
    events = 0
    for rec in parse_xplane(data):
        if not _is_device_line(rec["plane"], rec["line"]):
            continue
        cat = categorize_event_name(rec["name"])
        if cat is None:
            continue
        secs = rec["dur_ps"] / 1e12
        cats[cat] += secs
        busy += secs
        events += rec["count"]
    return {"categories": cats, "device_busy_s": busy, "events": events}
