"""Microbenchmark suite (reference: python/ray/_private/ray_perf.py —
`ray microbenchmark`: put/get/task/actor ops-per-second).

Run: python -m ray_tpu._private.perf [--quick]
Each line: name, ops/s (mean over trials).
"""

from __future__ import annotations

import time


def timeit(
    name: str, fn, multiplier: int = 1, trials: int = 3, warmup: bool = True
) -> dict:
    if warmup:
        fn()
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        rates.append(multiplier / dt)
    rate = sum(rates) / len(rates)
    print(f"{name:<46s} {rate:>12.1f} ops/s")
    return {"name": name, "ops_per_s": rate}


def main(quick: bool = False) -> list[dict]:
    import numpy as np

    import ray_tpu

    n = 100 if quick else 1000
    results = []
    ray_tpu.init(num_cpus=4)
    try:
        small = b"x" * 100
        big = np.zeros((1024, 1024), np.uint8)  # 1 MiB

        def put_small():
            for _ in range(n):
                ray_tpu.put(small)

        results.append(timeit("put (100 B)", put_small, n))

        ref_small = ray_tpu.put(small)

        def get_small():
            for _ in range(n):
                ray_tpu.get(ref_small)

        results.append(timeit("get (100 B, cached owner)", get_small, n))

        def put_big():
            for _ in range(max(n // 10, 10)):
                ray_tpu.put(big)

        results.append(timeit("put (1 MiB)", put_big, max(n // 10, 10)))

        @ray_tpu.remote
        def noop():
            return b"ok"

        def task_sync():
            for _ in range(max(n // 10, 10)):
                ray_tpu.get(noop.remote())

        results.append(
            timeit("task submit+get (sync)", task_sync, max(n // 10, 10))
        )

        def task_async():
            ray_tpu.get([noop.remote() for _ in range(n)])

        results.append(timeit(f"tasks async x{n}", task_async, n))

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.x = 0

            def inc(self):
                self.x += 1
                return self.x

        c = Counter.remote()

        def actor_sync():
            for _ in range(max(n // 10, 10)):
                ray_tpu.get(c.inc.remote())

        results.append(
            timeit("actor call (sync)", actor_sync, max(n // 10, 10))
        )

        def actor_async():
            ray_tpu.get([c.inc.remote() for _ in range(n)])

        results.append(timeit(f"actor calls async x{n}", actor_async, n))
        ray_tpu.kill(c)

        # Queued-task stress (reference envelope: 1M tasks queued on one
        # node, release/benchmarks/README.md:32 — scaled to CI time):
        # submit a burst far beyond worker capacity, drain it all.
        burst = 1000 if quick else 10_000

        def queue_burst():
            ray_tpu.get(
                [noop.remote() for _ in range(burst)], timeout=600
            )

        # warmup=False: running a 10k burst twice for one measurement
        # doubles the suite's most expensive bench for no signal.
        results.append(timeit(f"queued burst x{burst}", queue_burst, burst,
                              trials=1, warmup=False))
        results.extend(serve_bench(quick=quick))
        results.extend(object_plane_bench(quick=quick))
        results.extend(dag_pipeline_bench(quick=quick))
    finally:
        ray_tpu.shutdown()
    results.extend(collective_bench(quick=quick))
    results.extend(collective_multiproc_bench(quick=quick))
    results.extend(llm_decode_bench(quick=quick))
    return results


def llm_decode_bench(quick: bool = False) -> list[dict]:
    """Continuous-batching decode throughput through the PAGED engine
    (reference capability: vLLM's paged decode behind ray.llm). 64
    concurrent variable-length requests share a page pool the dense
    slab layout could not hold; the metric is aggregate sampled
    tokens/s through engine.step() — it catches structural regressions
    (per-step recompiles, logits host round-trips, allocator churn)
    wherever it runs; absolute rates only mean much on TPU."""
    import jax

    from ray_tpu.llm.engine import LLMEngine, SamplingParams
    from ray_tpu.models.llama import PRESETS

    cfg = PRESETS["tiny"]
    n_req = 16 if quick else 64
    max_tokens = 8 if quick else 32
    engine = LLMEngine(
        cfg, max_batch=8, max_seq=128, kv="paged", page_size=32,
        num_pages=28,
    )
    prompts = [
        [(7 * i + j) % cfg.vocab_size for j in range(2 + i % 13)]
        for i in range(n_req)
    ]
    # Warm the compile caches (prefill buckets + decode program).
    engine.generate(prompts[:4], SamplingParams(max_tokens=2))
    for p in prompts:
        engine.add_request(p, SamplingParams(max_tokens=max_tokens))
    tokens = 0
    t0 = time.perf_counter()
    while engine.has_unfinished():
        for fin in engine.step():
            tokens += len(fin["tokens"])
    dt = time.perf_counter() - t0
    rec = {
        "name": f"llm paged decode x{n_req} reqs",
        "tokens_per_s": round(tokens / dt, 1),
        "backend": jax.default_backend(),
    }
    print(f"{rec['name']:<46s} {rec['tokens_per_s']:>8.1f} tok/s "
          f"({rec['backend']})")
    return [rec]


def serve_bench(quick: bool = False) -> list[dict]:
    """Serve data-plane throughput and latency (reference: serve release
    microbenchmarks, python/ray/serve/benchmarks/microbenchmark.py —
    handle throughput, HTTP throughput, streaming TTFB)."""
    import concurrent.futures
    import json as _json
    import socket

    from ray_tpu import serve

    results: list[dict] = []

    @serve.deployment(max_ongoing_requests=64)
    class Echo:
        async def __call__(self, request):
            body = request.get("body") if isinstance(request, dict) else None
            if isinstance(body, dict) and body.get("stream"):
                return self._gen()
            return "ok"

        async def _gen(self):
            for i in range(8):
                yield {"i": i}

    handle = serve.run(Echo.bind(), name="_perf", route_prefix="/perf")
    port = serve.start_http()
    try:
        n = 200 if quick else 1000

        # Handle path: concurrent calls through the router.
        def handle_burst():
            responses = [handle.remote(None) for _ in range(n)]
            for r in responses:
                r.result(timeout=60)

        results.append(timeit(f"serve handle calls x{n}", handle_burst, n))

        # HTTP path: 8 keep-alive connections, n requests total.
        def http_worker(count: int):
            with socket.create_connection(
                ("127.0.0.1", port), timeout=30
            ) as s:
                req = b"GET /perf HTTP/1.1\r\nHost: x\r\n\r\n"
                for _ in range(count):
                    s.sendall(req)
                    buf = b""
                    while not buf.endswith(b"ok"):
                        chunk = s.recv(4096)
                        if not chunk:
                            raise RuntimeError("connection closed")
                        buf += chunk

        def http_burst():
            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                list(pool.map(http_worker, [n // 8] * 8))

        results.append(timeit(f"serve http req x{n}", http_burst, n))

        # Streaming TTFB: time from connect to the first SSE frame.
        payload = _json.dumps({"stream": True}).encode()
        req = (
            f"POST /perf HTTP/1.1\r\nHost: x\r\n"
            f"Accept: text/event-stream\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n"
        ).encode() + payload
        ttfbs = []
        for _ in range(20 if quick else 50):
            with socket.create_connection(
                ("127.0.0.1", port), timeout=30
            ) as s:
                t0 = time.perf_counter()
                s.sendall(req)
                buf = b""
                while b"data: " not in buf:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise RuntimeError("stream closed before first frame")
                    buf += chunk
                ttfbs.append(time.perf_counter() - t0)
                while b"[DONE]" not in buf:
                    chunk = s.recv(65536)
                    if not chunk:
                        raise RuntimeError("stream closed before [DONE]")
                    buf += chunk
        ttfbs.sort()
        rec = {
            "name": "serve sse ttfb",
            "p50_ms": round(ttfbs[len(ttfbs) // 2] * 1e3, 2),
            "p99_ms": round(ttfbs[int(len(ttfbs) * 0.99)] * 1e3, 2),
        }
        print(f"{rec['name']:<46s} p50={rec['p50_ms']}ms p99={rec['p99_ms']}ms")
        results.append(rec)
    finally:
        serve.shutdown()
    return results


def object_plane_bench(quick: bool = False) -> list[dict]:
    """Broadcast envelope (BASELINE.md: the reference's scalability
    envelope is a 1 GiB object broadcast to 50+ nodes riding
    push_manager chunked pushes; here 8 simulated nodes with separate
    store dirs on one host — the metric is aggregate store-to-store
    GB/s through the relay waves)."""
    import shutil
    import tempfile

    import numpy as np

    from ray_tpu import api as core_api
    from ray_tpu.runtime.node import NodeManager

    rt = core_api._runtime
    import ray_tpu

    n_nodes = 8
    nbytes = (64 << 20) if quick else (1 << 30)
    payload = np.random.default_rng(0).integers(
        0, 255, size=nbytes, dtype=np.uint8
    )

    # Store dirs on /dev/shm like the real per-node plasma pools — a
    # disk-backed tempdir benchmarks the disk, not the object plane.
    import os

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    dirs = [
        tempfile.mkdtemp(prefix=f"bcast{i}_", dir=base)
        for i in range(n_nodes)
    ]
    nodes = []

    async def launch(d):
        node = NodeManager(rt.core.head_addr, d, resources={"CPU": 0.01})
        await node.start()
        return node

    results: list[dict] = []
    try:
        for d in dirs:
            nodes.append(rt.run(launch(d)))
        ref = ray_tpu.put(payload)
        t0 = time.perf_counter()
        reply = ray_tpu.broadcast(ref, timeout=600, return_details=True)
        dt = time.perf_counter() - t0
        n = reply["nodes"]
        agg = n * nbytes / dt / 1e9
        rec = {
            "name": f"broadcast {nbytes >> 20} MiB x{n} nodes",
            "s": round(dt, 3),
            "agg_GB_s": round(agg, 2),
            # Relay-tree depth — deterministic, so CI can floor it even
            # when the memcpy-bound GB/s is noisy.
            "waves": reply["waves"],
        }
        print(
            f"{rec['name']:<46s} {dt:>8.2f}s  {agg:>6.2f} GB/s aggregate"
            f"  ({rec['waves']} waves)"
        )
        results.append(rec)
    finally:
        for node in nodes:
            try:
                rt.run(node.stop())
            # tpulint: allow(broad-except reason=bench teardown of throwaway nodes; the rows are already collected and shutdown() reaps leftovers)
            except Exception:  # noqa: BLE001
                pass
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
    return results


def dag_pipeline_bench(quick: bool = False) -> list[dict]:
    """Compiled-DAG pipeline throughput (reference: compiled graphs
    execution, compiled_dag_node.py). The reference's overlapped
    schedule hides NCCL latency behind GPU compute; the host-thread
    analogue measured net-negative here at small AND 8 MiB payloads
    (GIL-serialized copies) and was removed — the ShmChannel ring
    already pipelines across actors.

    Submission is WINDOWED: a compiled pipeline only buffers
    nslots×stages executions, so submit-all-then-read deadlocks past
    that depth.
    """
    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def work(self, x):
            return x + 1

    n_exec = 300 if quick else 2000
    stages = [Stage.remote() for _ in range(3)]
    with InputNode() as inp:
        node = inp
        for s in stages:
            node = s.work.bind(node)
        dag = node.experimental_compile()
    try:
        dag.execute(0).get(timeout=60)  # warm the loops
        t0 = time.perf_counter()
        window = []
        for i in range(n_exec):
            window.append(dag.execute(i))
            if len(window) >= 6:
                window.pop(0).get(timeout=120)
        while window:
            window.pop(0).get(timeout=120)
        dt = time.perf_counter() - t0
    finally:
        dag.teardown()
        for s in stages:
            try:
                ray_tpu.kill(s)
            # tpulint: allow(broad-except reason=bench teardown of throwaway stage actors; the measurement is already taken)
            except Exception:  # noqa: BLE001
                pass
    rate = n_exec / dt
    rec = {"name": "dag 3-stage pipeline", "ops_per_s": rate}
    print(f"{rec['name']:<46s} {rate:>12.1f} ops/s")
    return [rec]


def collective_multiproc_bench(quick: bool = False) -> list[dict]:
    """Allreduce bus bandwidth across REAL process boundaries: N
    subprocesses form one gloo jax world and allreduce a shared-size
    payload (BASELINE.json config 1: the NCCL-vs-Gloo allreduce sweep —
    this is the honest single-host proxy, unlike a 1-device 'allreduce'
    which is a copy)."""
    import json as _json
    import os
    import socket
    import subprocess
    import sys
    import tempfile
    import textwrap

    results: list[dict] = []
    nbytes = (8 << 20) if quick else (64 << 20)
    worlds = (2,) if quick else (2, 4, 8)
    trials = 3

    script = textwrap.dedent(
        """
        import os, time, json
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address="127.0.0.1:{port}",
            num_processes={world},
            process_id={rank},
        )
        import jax.numpy as jnp
        from ray_tpu.collective.backends.xla_group import XlaDistGroup

        g = XlaDistGroup({world}, {rank})
        x = jnp.ones(({nelem},), jnp.float32)
        out = g.allreduce(x)
        float(out[0])  # compile + sync
        g.barrier()
        t0 = time.perf_counter()
        for _ in range({trials}):
            out = g.allreduce(out)
        float(out[0])
        dt = (time.perf_counter() - t0) / {trials}
        if {rank} == 0:
            print("DT=" + json.dumps(dt))
        """
    )

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    import ray_tpu as _rt

    repo_root = os.path.dirname(os.path.dirname(_rt.__file__))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH", "")) if p
    )

    for world in worlds:
        port = free_port()
        procs = []
        try:
            with tempfile.TemporaryDirectory() as td:
                for rank in range(world):
                    path = os.path.join(td, f"r{rank}.py")
                    with open(path, "w") as f:
                        f.write(
                            script.format(
                                port=port,
                                world=world,
                                rank=rank,
                                nelem=nbytes // 4,
                                trials=trials,
                            )
                        )
                    procs.append(
                        subprocess.Popen(
                            [sys.executable, path],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT,
                            text=True,
                            env=env,
                        )
                    )
                outs = [p.communicate(timeout=300)[0] for p in procs]
        finally:
            # One wedged rank (port race, import error → the others
            # block in initialize forever) must not orphan the rest.
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rank, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise RuntimeError(
                    f"gloo bench rank{rank}/{world} rc={p.returncode}:"
                    f"\n{out[-2000:]}"
                )
        dt = next(
            _json.loads(line[3:])
            for line in outs[0].splitlines()
            if line.startswith("DT=")
        )
        bus = 2 * (world - 1) / world * nbytes / dt / 1e9
        rec = {
            "name": f"allreduce gloo {nbytes >> 20} MiB {world}p",
            "per_s": round(1.0 / dt, 2),
            "bus_GB_s": round(bus, 3),
        }
        print(
            f"{rec['name']:<46s} {rec['per_s']:>8.2f}/s "
            f"{rec['bus_GB_s']:>7.3f} GB/s bus"
        )
        results.append(rec)
    return results


def collective_bench(quick: bool = False) -> list[dict]:
    """Allreduce bus bandwidth on the XLA mesh backend vs the naive host
    path (BASELINE.json config 1: NCCL-vs-Gloo analogue — here XLA
    collectives over the device mesh vs single-host numpy reduce)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    results: list[dict] = []
    nbytes = (4 << 20) if quick else (64 << 20)  # per-shard payload
    n_elem = nbytes // 4
    world = len(devs)
    trials = 5

    # XLA path: psum over every device on the mesh (ICI on real TPUs).
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu._private.jax_compat import shard_map

    mesh = Mesh(np.asarray(devs, object).reshape(world), ("x",))
    shards = jax.device_put(
        jnp.ones((world, n_elem), jnp.float32),
        NamedSharding(mesh, P("x", None)),
    )
    allreduce = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a, "x"),
            mesh=mesh,
            in_specs=P("x", None),
            out_specs=P("x", None),
        )
    )
    def bus_gb_s(dt: float) -> float:
        # Ring-allreduce bus-bandwidth convention: 2(w-1)/w * bytes/t.
        factor = 2 * (world - 1) / world if world > 1 else 1.0
        return round(factor * nbytes / dt / 1e9, 2)

    if world > 1:
        # A single-device "allreduce" is a copy, not a collective — the
        # mesh entry only means something with 2+ devices; the honest
        # single-host collective number is collective_multiproc_bench.
        out = allreduce(shards)
        float(out[0, 0])  # compile + sync
        t0 = time.perf_counter()
        for _ in range(trials):
            out = allreduce(out)
        float(out[0, 0])
        dt = (time.perf_counter() - t0) / trials
        results.append({
            "name": f"allreduce xla_mesh {nbytes >> 20} MiB x{world}dev",
            "per_s": 1.0 / dt,
            "bus_GB_s": bus_gb_s(dt),
        })
        print(results[-1])

    # Host baseline: numpy sum over per-rank buffers (the Gloo stand-in).
    host = [np.ones(n_elem, np.float32) for _ in range(world)]
    t0 = time.perf_counter()
    for _ in range(trials):
        reduced = np.sum(host, axis=0)
        host = [reduced.copy() for _ in range(world)]
    dt_host = (time.perf_counter() - t0) / trials
    results.append({
        "name": f"allreduce host-numpy {nbytes >> 20} MiB x{world}",
        "per_s": 1.0 / dt_host,
        "bus_GB_s": bus_gb_s(dt_host),
    })
    print(results[-1])
    return results


if __name__ == "__main__":
    import json
    import sys

    results = main(quick="--quick" in sys.argv)
    for i, a in enumerate(sys.argv):
        if a == "--json" and i + 1 < len(sys.argv):
            with open(sys.argv[i + 1], "w") as f:
                json.dump(
                    {
                        "results": results,
                        "note": "control-plane microbenchmarks "
                                "(ray_perf.py equivalent); floors "
                                "enforced by tests/test_perf_floors.py",
                    },
                    f,
                    indent=2,
                )
            print(f"wrote {sys.argv[i + 1]}")
