"""Cross-language function registry: Python functions callable from
non-Python drivers by NAME with msgpack arguments.

Reference: the cross-language model in python/ray/cross_language.py +
cpp/include/ray/api/ray_remote.h — callees register functions under
stable descriptors, callers in another language submit tasks naming the
descriptor, and arguments/results cross the boundary as msgpack (the
reference's cross-language serialization format), never pickle.

Here a registered function lives in the head KV under ``xfn:<name>``
(the same export path pickled Python tasks use — workers fetch and
cache by id); a foreign driver (cpp/ client) leases a worker and pushes
a task spec with ``fn_id="xfn:<name>"``, ``xlang=True`` and
msgpack-encoded args; the worker replies with a msgpack-encoded result
inline.
"""

from __future__ import annotations

from typing import Callable


def register_function(name: str, fn: Callable) -> str:
    """Publish ``fn`` under ``xfn:<name>`` for cross-language callers.
    Arguments arrive as plain msgpack data (numbers, strings, bytes,
    lists, maps); the return value must be msgpack-encodable the same
    way."""
    if ":" in name:
        raise ValueError(f"cross-language names must not contain ':': {name!r}")
    from ray_tpu import api as core_api
    from ray_tpu.runtime.core_worker import serialize

    rt = core_api._runtime
    blob = serialize(fn).materialize_buffers()

    async def put():
        await rt.core.head.call(
            "kv_put",
            key=f"xfn:{name}",
            value=blob.inband + b"".join(blob.buffers),
            overwrite=True,
        )

    rt.run(put())
    return f"xfn:{name}"


def unregister_function(name: str) -> None:
    from ray_tpu import api as core_api

    rt = core_api._runtime

    async def drop():
        await rt.core.head.call("kv_del", key=f"xfn:{name}")

    rt.run(drop())
