"""Simulated-scale control-plane harness: thousands of fake nodes
against a REAL head.

The head under test is the production `HeadService`, CLI-daemonized in
its own process and spoken to over real RPC connections — nothing is
mocked on the head side. What is fake is the *nodes*: each `FakeNode`
is a few-hundred-byte asyncio object with a real listening socket (the
head dials back on registration), a keepalive loop, and a telemetry
flood generator. One harness process comfortably simulates a
1000-node cluster, which is how the head-survival fixes in this repo
were found and are pinned (`bench_head.py` → BENCH_head.json).

Legs (each emits `{"name", "value", "unit"}` JSON rows on stdout, the
same row protocol as scale_smoke.py, and contributes to the result
doc):

- register storm      N nodes register concurrently; registrations/s
                      and pick_node decisions/s over the full cluster.
- idle control p99    keepalive RTT percentiles with no competing load,
                      plus a contended baseline (harness burns the same
                      CPU with NO telemetry) to isolate head queueing
                      from shared-core contention.
- overdrive           unthrottled telemetry flood — calibrates fold
                      throughput and proves the bounded queue sheds
                      (counter + overload alert).
- 2x overload         telemetry flood throttled to 2x the calibrated
                      fold throughput — the pinned criterion: control
                      RPC p99 must hold within bound while shedding.
- slice mass death    a labelled 32-node slice dies at once; death +
                      drain fan-out must coalesce (pushed frames <<
                      logical msgs x subscribers).
- SIGKILL recovery    head killed mid-load, restarted via the CLI;
                      journal replay + full re-registration timed, with
                      the jittered reconnect backoff observed.

Run reduced (tier-1 smoke): python -m ray_tpu._private.scale_sim \
    --nodes 12 --slice-nodes 4 --subscribers 3 --overload-s 2
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import secrets
import signal
import socket
import statistics
import subprocess
import sys
import time

KEEPALIVE_INTERVAL_S = 1.0
HEALTH_TIMEOUT_S = 4.0
FLOOD_BATCH = 500


def emit(name: str, value, unit: str) -> dict:
    row = {"name": name, "value": value, "unit": unit}
    print(json.dumps(row), flush=True)
    return row


def _pct(xs: "list[float]", q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _raise_fd_limit() -> None:
    """3 sockets per fake node (listener + conn each way) — lift the
    soft RLIMIT_NOFILE to the hard cap so 1000 nodes fit. The head
    subprocess inherits the raised limit."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))


class HeadProc:
    """The real head, CLI-daemonized (`ray_tpu start --head
    --head-only`) so a SIGKILL is a genuine process death — no shared
    event loop with the harness to soften the crash."""

    def __init__(self, session_dir: str, port: int, token: str,
                 extra_env: "dict[str, str] | None" = None):
        self.session_dir = session_dir
        self.port = port
        self.token = token
        self.addr = f"127.0.0.1:{port}"
        self.extra_env = dict(extra_env or {})

    def _cli(self, args: "list[str]") -> subprocess.CompletedProcess:
        env = dict(os.environ)
        env.update(self.extra_env)
        env["JAX_PLATFORMS"] = "cpu"
        return subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", *args],
            capture_output=True, text=True, timeout=120, env=env,
        )

    def start(self) -> None:
        out = self._cli(
            ["start", "--head", "--head-only",
             "--port", str(self.port),
             "--session-dir", self.session_dir,
             "--auth-token", self.token]
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"head start failed: {out.stdout}\n{out.stderr}"
            )

    def pid(self) -> int:
        pids = [
            int(open(os.path.join(self.session_dir, f)).read())
            for f in os.listdir(self.session_dir)
            if f.startswith("head-") and f.endswith(".pid")
        ]
        if not pids:
            raise RuntimeError("no head pid file in session dir")
        return pids[0]

    def sigkill(self) -> None:
        os.kill(self.pid(), signal.SIGKILL)
        for f in list(os.listdir(self.session_dir)):
            if f.endswith(".pid"):
                os.unlink(os.path.join(self.session_dir, f))

    def stop(self) -> None:
        try:
            self._cli(["stop", "--session-dir", self.session_dir])
        # tpulint: allow(broad-except reason=bench teardown is best-effort; the head may already be SIGKILLed by the recovery leg and `stop` failing then is the expected outcome)
        except Exception:
            pass


class FakeNode:
    """A lightweight node impostor: registers with real labels and a
    real listening socket, keeps its heartbeat alive, floods telemetry
    on demand, and — after a head death — re-registers through the
    same jittered exponential backoff the production
    ReconnectingClient uses, recording the delays it drew."""

    def __init__(self, idx: int, head_addr: str,
                 labels: "dict | None" = None):
        self.idx = idx
        self.node_id = f"sim{idx:05d}" + secrets.token_hex(4)
        self.head_addr = head_addr
        self.labels = labels or {}
        self.server = None
        self.addr = None
        self.conn = None
        self._keepalive_task = None
        self.dead = False
        self.keepalive_rtts: "list[float]" = []
        self.backoff_delays: "list[float]" = []
        self.reregistered_ts: "float | None" = None
        self._span_seq = 0

    async def _serve(self, method: str, kw: dict, conn) -> dict:
        # set_draining, probes — a fake node agrees with everything.
        return {"ok": True}

    async def start(self) -> None:
        from ray_tpu._private import rpc

        self.server = rpc.Server(self._serve)
        port = await self.server.start("127.0.0.1", 0)
        self.addr = f"127.0.0.1:{port}"
        await self._register()

    async def _register(self) -> None:
        from ray_tpu._private import rpc

        self.conn = await rpc.connect(self.head_addr)
        await self.conn.call(
            "register_node",
            node_id=self.node_id,
            addr=self.addr,
            resources={"CPU": 4.0, "TPU": 4.0},
            labels=self.labels,
        )

    async def _reconnect(self) -> None:
        """Post-head-death reconnect: full-jitter exponential backoff,
        exactly the production schedule (rpc.backoff_delay), with each
        drawn delay recorded so the harness can assert the herd
        actually spread out."""
        from ray_tpu._private import rpc

        attempt = 0
        while not self.dead:
            delay = rpc.backoff_delay(attempt)
            self.backoff_delays.append(delay)
            await asyncio.sleep(delay)
            try:
                await self._register()
                self.reregistered_ts = time.monotonic()
                return
            except (rpc.RpcError, OSError):
                attempt += 1

    async def keepalive_loop(self) -> None:
        from ray_tpu._private import rpc

        while not self.dead:
            await asyncio.sleep(
                KEEPALIVE_INTERVAL_S * (0.5 + self.idx % 100 / 100.0)
            )
            if self.dead:
                return
            t0 = time.monotonic()
            try:
                reply = await self.conn.call("keepalive",
                                             node_id=self.node_id)
                self.keepalive_rtts.append(time.monotonic() - t0)
                if reply.get("reregister"):
                    await self._register()
            except (rpc.RpcError, OSError):
                if not self.dead:
                    await self._reconnect()

    def start_keepalive(self) -> None:
        self._keepalive_task = asyncio.ensure_future(
            self.keepalive_loop()
        )

    def make_events(self, n: int) -> "list[dict]":
        out = []
        for _ in range(n):
            self._span_seq += 1
            out.append({
                "task_id": f"{self.node_id}-t{self._span_seq}",
                "name": "sim_task",
                "state": "FINISHED",
                "worker": self.addr,
                "ts": time.time(),
                "dur": 0.01,
            })
        return out

    async def flood(self, until: float,
                    interval_s: "float | None",
                    phase: float = 0.0) -> int:
        """Send FLOOD_BATCH-event telemetry batches until the deadline;
        `interval_s` rate-limits (None = as fast as possible).
        Returns events sent. Payloads are prebuilt and cycled so the
        harness spends its cycles on the wire, not on dict literals —
        otherwise a single-core box caps the send rate at roughly the
        head's fold rate and the overload legs can't outrun it."""
        from ray_tpu._private import rpc

        payloads = [self.make_events(FLOOD_BATCH) for _ in range(4)]
        sent = 0
        i = 0
        # Absolute schedule: next_t advances by the interval regardless
        # of call RTT, so a slow reply is caught up with back-to-back
        # sends instead of silently lowering the achieved rate. The
        # phase offset (fraction of one interval) de-synchronizes the
        # flooder fleet: phase-locked senders all firing on the same
        # tick deliver their frames as one burst, and the head decodes
        # them back-to-back — a tens-of-ms tail-latency artifact real
        # (unsynchronized) nodes don't produce.
        next_t = time.monotonic()
        if interval_s and phase:
            offset = phase * interval_s
            next_t += offset
            await asyncio.sleep(offset)
        while time.monotonic() < until and not self.dead:
            try:
                await self.conn.call(
                    "add_task_events",
                    events=payloads[i % len(payloads)],
                )
                i += 1
                sent += FLOOD_BATCH
            except (rpc.RpcError, OSError):
                return sent
            if interval_s:
                next_t += interval_s
                await asyncio.sleep(max(0.0, next_t - time.monotonic()))
            else:
                await asyncio.sleep(0)
        return sent

    async def kill(self) -> None:
        """Die abruptly: stop answering, close every socket. The head
        finds out the way it would in production — heartbeat timeout."""
        self.dead = True
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        if self.conn is not None:
            await self.conn.close()
        if self.server is not None:
            await self.server.stop()


class Subscriber:
    """A pubsub client counting frames vs logical messages — the
    receiving end of the death fan-out coalescing assertion."""

    def __init__(self, head_addr: str):
        self.head_addr = head_addr
        self.conn = None
        self.frames = 0
        self.msgs = 0

    def _on_push(self, payload) -> None:
        self.frames += 1
        batch = payload.get("batch")
        self.msgs += len(batch) if batch is not None else 1

    async def start(self, channels=("node", "drain", "slice")) -> None:
        from ray_tpu._private import rpc

        self.conn = await rpc.connect(self.head_addr,
                                      on_push=self._on_push)
        for ch in channels:
            await self.conn.call("subscribe", channel=ch)

    async def close(self) -> None:
        if self.conn is not None:
            await self.conn.close()


async def _head_stats(head_addr: str) -> dict:
    from ray_tpu._private import rpc

    conn = await rpc.connect(head_addr)
    try:
        return await conn.call("head_stats")
    finally:
        await conn.close()


class RttSampler:
    """Control-RPC latency probe in its OWN process: the harness
    process is GIL-saturated by the flooders during the overload leg,
    and a sampler sharing it (task or thread) measures harness GIL
    starvation, not head responsiveness. The subprocess connects,
    prints READY, samples for the window, and prints the RTT list."""

    def __init__(self, head_addr: str, node_id: str, seconds: float):
        self._args = [
            sys.executable, "-m", "ray_tpu._private.scale_sim",
            "--sample-rtt", head_addr, "--node-id", node_id,
            "--seconds", str(seconds),
        ]
        self._proc = None

    async def start(self) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"

        def _nice():
            # Latency measurement discipline: the sampler is nearly
            # idle, but on a loaded single-core box its RTTs would
            # otherwise include its OWN run-queue wakeup latency (tens
            # of ms under CFS) — priority removes the artifact without
            # distorting head-vs-flooder competition. Best effort.
            try:
                os.nice(-10)
            except OSError:
                pass

        self._proc = await asyncio.create_subprocess_exec(
            *self._args,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env=env,
            preexec_fn=_nice,
        )
        ready = await asyncio.wait_for(
            self._proc.stdout.readline(), timeout=60
        )
        if ready.strip() != b"READY":
            raise RuntimeError(f"rtt sampler did not start: {ready!r}")

    async def result(self) -> "list[float]":
        out, err = await self._proc.communicate()
        if self._proc.returncode != 0:
            raise RuntimeError(f"rtt sampler failed: {err.decode()}")
        return json.loads(out)


async def _sample_control_rtt(head_addr: str, node_id: str,
                              seconds: float) -> "list[float]":
    s = RttSampler(head_addr, node_id, seconds)
    await s.start()
    return await s.result()


def _sample_rtt_main(addr: str, node_id: str, seconds: float) -> int:
    async def sample() -> "list[float]":
        from ray_tpu._private import rpc

        conn = await rpc.connect(addr)
        print("READY", flush=True)
        rtts = []
        until = time.monotonic() + seconds
        try:
            while time.monotonic() < until:
                t0 = time.monotonic()
                await conn.call("keepalive", node_id=node_id)
                rtts.append(time.monotonic() - t0)
                await asyncio.sleep(0.005)
        finally:
            await conn.close()
        return rtts

    print(json.dumps(asyncio.run(sample())), flush=True)
    return 0


async def _pick_rate(head_addr: str, seconds: float) -> float:
    """Scheduler decisions/s over the registered cluster."""
    from ray_tpu._private import rpc

    conn = await rpc.connect(head_addr)
    n = 0
    until = time.monotonic() + seconds
    t0 = time.monotonic()
    try:
        while time.monotonic() < until:
            await conn.call("pick_node", resources={"CPU": 1.0})
            n += 1
    finally:
        took = time.monotonic() - t0
        await conn.close()
    return n / max(took, 1e-9)


async def run_sim(opts) -> dict:
    from ray_tpu._private import rpc

    doc: dict = {
        "bench": "head_scale",
        "nodes": opts.nodes,
        "slice_nodes": opts.slice_nodes,
        "subscribers": opts.subscribers,
    }
    token = secrets.token_hex(16)
    os.environ["RAY_TPU_AUTH_TOKEN"] = token
    journal = os.path.join(opts.session_dir, "head.journal")
    head = HeadProc(
        opts.session_dir, opts.port or _free_port(), token,
        extra_env={
            "RAY_TPU_HEAD_JOURNAL": journal,
            "RAY_TPU_HEALTH_TIMEOUT_S": str(HEALTH_TIMEOUT_S),
            "RAY_TPU_HEAD_FOLD_QUEUE_MAX": str(opts.fold_queue_max),
            # Control plane wins CPU contention against the co-located
            # load generator (the documented shared-host deployment
            # posture; best-effort without privileges).
            "RAY_TPU_HEAD_NICE": "-5",
        },
    )
    head.start()
    nodes: "list[FakeNode]" = []
    try:
        # --- leg 1: registration storm -------------------------------
        t0 = time.monotonic()
        plain = opts.nodes - opts.slice_nodes
        for i in range(opts.nodes):
            labels = (
                {"slice": "simslice", "slice_host_count": opts.slice_nodes}
                if i >= plain else {}
            )
            nodes.append(FakeNode(i, head.addr, labels=labels))
        sem = asyncio.Semaphore(64)

        async def boot(n: FakeNode):
            async with sem:
                await n.start()

        await asyncio.gather(*(boot(n) for n in nodes))
        reg_s = time.monotonic() - t0
        doc["register_storm"] = {
            "nodes": opts.nodes,
            "wall_s": round(reg_s, 3),
            "registrations_per_s": round(opts.nodes / reg_s, 1),
        }
        emit("head_register_per_s", doc["register_storm"]
             ["registrations_per_s"], "regs/s")
        for n in nodes:
            n.start_keepalive()

        # Scheduler decision rate over the full maintained columns.
        pick_rate = await _pick_rate(head.addr, opts.probe_s)
        doc["pick_node_per_s"] = round(pick_rate, 1)
        emit("head_pick_node_per_s", doc["pick_node_per_s"], "picks/s")

        # --- leg 2: idle control p99 ---------------------------------
        idle = await _sample_control_rtt(
            head.addr, nodes[0].node_id, opts.probe_s
        )
        doc["idle_control_p50_ms"] = round(_pct(idle, 0.5) * 1e3, 3)
        doc["idle_control_p99_ms"] = round(_pct(idle, 0.99) * 1e3, 3)
        emit("head_idle_control_p99_ms", doc["idle_control_p99_ms"],
             "ms")

        # --- leg 2b: contended baseline ------------------------------
        # On a shared-core box the overload leg's keepalive RTT folds
        # in two costs the head's admission classes cannot touch: the
        # load generator's own CPU burn, and the OS run-queue delay a
        # SATURATED process pays under CFS (a busy head burns its
        # timeslice and then waits behind its neighbours — multi-ms at
        # the tail, and absent on any multi-core production box).
        # Baseline both out: the harness spins AND a burner subprocess
        # stands in for the busy head process, so overload_p99 /
        # contended_p99 isolates the queueing the head itself adds
        # under a span flood — the quantity the admission classes are
        # meant to bound.
        burner = subprocess.Popen(
            [sys.executable, "-c", "while True:\n    pass"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            baseline_s = max(opts.probe_s, 4.0)
            sampler = RttSampler(
                head.addr, nodes[0].node_id, baseline_s
            )
            await sampler.start()
            until = time.monotonic() + baseline_s
            while time.monotonic() < until:
                for _ in range(20000):
                    pass
                await asyncio.sleep(0)
            contended = await sampler.result()
        finally:
            burner.kill()
            burner.wait()
        doc["contended_control_p50_ms"] = round(
            _pct(contended, 0.5) * 1e3, 3
        )
        doc["contended_control_p99_ms"] = round(
            _pct(contended, 0.99) * 1e3, 3
        )
        emit("head_contended_control_p99_ms",
             doc["contended_control_p99_ms"], "ms")

        # --- leg 3: telemetry overload -------------------------------
        # Unthrottled flood: the prebuilt-payload senders enqueue far
        # faster than the head can fold, so the bounded queue fills and
        # MUST shed — while the control sampler (own process) measures
        # keepalive RTT through the storm. The overload factor
        # (enqueue rate / fold rate) is reported and pinned >= 2x.
        flooders = nodes[: min(32, len(nodes))]
        s1 = await _head_stats(head.addr)
        sampler = RttSampler(
            head.addr, nodes[0].node_id, opts.overload_s
        )
        await sampler.start()
        until = time.monotonic() + opts.overload_s
        sent = await asyncio.gather(
            *(n.flood(until, interval_s=None) for n in flooders)
        )
        rtts = await sampler.result()
        s2 = await _head_stats(head.addr)
        send_rate = sum(sent) / opts.overload_s
        fold_rate = (
            (s2["folded_total"] - s1["folded_total"]) / opts.overload_s
        )
        doc["fold_events_per_s"] = round(fold_rate, 1)
        emit("head_fold_events_per_s", doc["fold_events_per_s"],
             "events/s")
        doc["overload"] = {
            "events_sent": sum(sent),
            "send_events_per_s": round(send_rate, 1),
            "overload_factor": round(
                send_rate / max(fold_rate, 1.0), 2
            ),
            "shed_total": s2["shed_total"] - s1["shed_total"],
            "alert_seen": bool(
                s2["overload_alert"]
                or s2["shed_total"] > s1["shed_total"]
            ),
            "control_p50_ms": round(_pct(rtts, 0.5) * 1e3, 3),
            "control_p99_ms": round(_pct(rtts, 0.99) * 1e3, 3),
            "p99_vs_idle": round(
                _pct(rtts, 0.99) / max(_pct(idle, 0.99), 1e-9), 2
            ),
            "p99_vs_contended": round(
                _pct(rtts, 0.99) / max(_pct(contended, 0.99), 1e-9), 2
            ),
        }
        emit("head_overload_shed_total", doc["overload"]["shed_total"],
             "events")
        emit("head_overload_control_p99_ms",
             doc["overload"]["control_p99_ms"], "ms")
        # Let the fold backlog drain (alert OFF) before the next leg.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            s2 = await _head_stats(head.addr)
            if s2["fold_queue_depth"] == 0:
                break
            await asyncio.sleep(0.25)

        # --- leg 3b: 2x overload (the pinned criterion) --------------
        # The overdrive leg above proves the queue sheds at maximum
        # pressure; THIS leg is the acceptance criterion: control-RPC
        # p99 must hold while the head is fed ~2x what it can fold.
        # Fold capacity is load-dependent (lighter decode pressure =
        # higher capacity), so a fixed 2x-of-calibration target can
        # land UNDER true capacity and never shed — and capacity drops
        # steeply once decode saturates, so doubling overshoots to 6x.
        # Bisect the send rate to the factor~2 knee instead.
        target = 2.0 * max(fold_rate, 1.0)
        lo = hi = None  # send rates bracketing the knee
        attempts = []
        for _attempt in range(8):
            per_flooder_interval = (
                FLOOD_BATCH * len(flooders) / target
            )
            s1 = await _head_stats(head.addr)
            sampler = RttSampler(
                head.addr, nodes[0].node_id, opts.overload_s
            )
            await sampler.start()
            until = time.monotonic() + opts.overload_s
            sent = await asyncio.gather(
                *(n.flood(until, interval_s=per_flooder_interval,
                          phase=i / len(flooders))
                  for i, n in enumerate(flooders))
            )
            rtts2 = await sampler.result()
            s2 = await _head_stats(head.addr)
            send_rate2 = sum(sent) / opts.overload_s
            fold_rate2 = (
                (s2["folded_total"] - s1["folded_total"])
                / opts.overload_s
            )
            leg = {
                "target_events_per_s": round(target, 1),
                "send_events_per_s": round(send_rate2, 1),
                "overload_factor": round(
                    send_rate2 / max(fold_rate2, 1.0), 2
                ),
                "shed_total": s2["shed_total"] - s1["shed_total"],
                "control_p50_ms": round(_pct(rtts2, 0.5) * 1e3, 3),
                "control_p99_ms": round(_pct(rtts2, 0.99) * 1e3, 3),
                "p99_vs_idle": round(
                    _pct(rtts2, 0.99) / max(_pct(idle, 0.99), 1e-9), 2
                ),
                "p99_vs_contended": round(
                    _pct(rtts2, 0.99)
                    / max(_pct(contended, 0.99), 1e-9),
                    2,
                ),
            }
            attempts.append(leg)
            # Drain the backlog before judging / retrying.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sd = await _head_stats(head.addr)
                if sd["fold_queue_depth"] == 0:
                    break
                await asyncio.sleep(0.25)
            factor = leg["overload_factor"]
            if 1.8 <= factor <= 3.2 and leg["shed_total"] > 0:
                break
            if factor < 1.8:  # head kept up — push harder
                lo = send_rate2
                target = (
                    (lo * hi) ** 0.5 if hi else 2.0 * send_rate2
                )
            else:  # overshot the knee — back off
                hi = send_rate2
                target = (lo * hi) ** 0.5 if lo else hi / 2.0
        # Keep the attempt that best realized "2x overload" (closest
        # factor to 2 among those that shed AND genuinely overloaded
        # the head) — the bisection's last probe is not necessarily
        # its best.
        import math

        def _score(a):
            f = max(a["overload_factor"], 1e-6)
            # Sub-1.5x attempts didn't meaningfully overload the head;
            # only prefer one if nothing better exists.
            return (0 if f >= 1.5 else 100) + abs(math.log(f / 2.0))

        best = min(
            (a for a in attempts if a["shed_total"] > 0),
            key=_score,
            default=attempts[-1],
        )
        doc["overload_2x"] = dict(best, attempts=len(attempts))
        emit("head_overload2x_control_p99_ms",
             doc["overload_2x"]["control_p99_ms"], "ms")

        # --- leg 4: slice mass death ---------------------------------
        subs = [Subscriber(head.addr) for _ in range(opts.subscribers)]
        for s in subs:
            await s.start()
        sd0 = await _head_stats(head.addr)
        victims = [n for n in nodes if n.labels.get("slice")]
        t_kill = time.monotonic()
        await asyncio.gather(*(n.kill() for n in victims))
        # Death is discovered by heartbeat timeout; wait for the table
        # to shrink to the survivors.
        survivors = opts.nodes - len(victims)
        deadline = time.monotonic() + HEALTH_TIMEOUT_S * 4 + 30
        while time.monotonic() < deadline:
            sd1 = await _head_stats(head.addr)
            if sd1["nodes"] <= survivors:
                break
            await asyncio.sleep(0.25)
        detect_s = time.monotonic() - t_kill
        await asyncio.sleep(0.5)
        sd1 = await _head_stats(head.addr)
        msgs = sd1["pub_msgs_total"] - sd0["pub_msgs_total"]
        pushes = sd1["pub_pushes_total"] - sd0["pub_pushes_total"]
        naive = msgs * max(1, len(subs))
        doc["mass_death"] = {
            "victims": len(victims),
            "subscribers": len(subs),
            "detect_s": round(detect_s, 2),
            "logical_msgs": msgs,
            "pushed_frames": pushes,
            "naive_frames": naive,
            "coalesce_ratio": round(pushes / max(naive, 1), 4),
            "sub_frames": [s.frames for s in subs],
            "sub_msgs": [s.msgs for s in subs],
        }
        emit("head_death_fanout_frames", pushes, "frames")
        emit("head_death_fanout_coalesce_ratio",
             doc["mass_death"]["coalesce_ratio"], "ratio")
        for s in subs:
            await s.close()

        # --- leg 5: mid-load head SIGKILL + recovery -----------------
        # Give the journal realistic replay depth first.
        conn = await rpc.connect(head.addr)
        for i in range(opts.journal_keys):
            await conn.call(
                "kv_put", key=f"scale:k{i}", value=b"x" * 128
            )
        await conn.close()
        live = [n for n in nodes if not n.dead]
        flood_until = time.monotonic() + 30
        flood_tasks = [
            asyncio.ensure_future(n.flood(flood_until, interval_s=0.1))
            for n in live[:8]
        ]
        await asyncio.sleep(0.5)
        t_kill = time.monotonic()
        head.sigkill()
        head.start()
        # First successful control RPC = journal replayed + serving.
        t_first = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                s3 = await _head_stats(head.addr)
                t_first = time.monotonic() - t_kill
                break
            except (rpc.RpcError, OSError):
                await asyncio.sleep(0.1)
        if t_first is None:
            raise RuntimeError("head never came back after SIGKILL")
        # Full recovery: every live fake node re-registered through its
        # jittered backoff loop.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s4 = await _head_stats(head.addr)
            if s4["nodes"] >= len(live):
                break
            await asyncio.sleep(0.25)
        t_full = time.monotonic() - t_kill
        for t in flood_tasks:
            t.cancel()
        delays = [d for n in live for d in n.backoff_delays]
        doc["sigkill_recovery"] = {
            "first_rpc_s": round(t_first, 2),
            "full_reconnect_s": round(t_full, 2),
            "reconnected": s4["nodes"],
            "expected": len(live),
            "replayed_records": (s3.get("journal") or {}).get(
                "replayed_records", 0
            ),
            "replay_s": (s3.get("journal") or {}).get("replay_s", 0.0),
            "backoff_draws": len(delays),
            "backoff_spread_s": round(
                (max(delays) - min(delays)) if len(delays) > 1 else 0.0,
                4,
            ),
            "backoff_stdev_s": round(
                statistics.pstdev(delays) if len(delays) > 1 else 0.0,
                4,
            ),
        }
        emit("head_recover_first_rpc_s", doc["sigkill_recovery"]
             ["first_rpc_s"], "s")
        emit("head_recover_full_s", doc["sigkill_recovery"]
             ["full_reconnect_s"], "s")
        emit("head_backoff_spread_s", doc["sigkill_recovery"]
             ["backoff_spread_s"], "s")
        doc["ok"] = True
        return doc
    finally:
        for n in nodes:
            if not n.dead:
                try:
                    await n.kill()
                # tpulint: allow(broad-except reason=bench teardown sweep; a node whose connection died mid-leg still must not block the remaining kills or the doc return)
                except Exception:
                    pass
        head.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="simulated-scale head survival harness"
    )
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--slice-nodes", type=int, default=32,
                    help="slice-labelled victims for the mass-death leg")
    ap.add_argument("--subscribers", type=int, default=8)
    ap.add_argument("--overload-s", type=float, default=5.0)
    ap.add_argument("--probe-s", type=float, default=2.0)
    ap.add_argument("--journal-keys", type=int, default=2000)
    ap.add_argument("--fold-queue-max", type=int, default=20000)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--session-dir", default=None)
    ap.add_argument("--out", default=None,
                    help="write the full result doc to this JSON file")
    ap.add_argument("--sample-rtt", default=None, metavar="ADDR",
                    help=argparse.SUPPRESS)  # internal: sampler child
    ap.add_argument("--node-id", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--seconds", type=float, default=2.0,
                    help=argparse.SUPPRESS)
    opts = ap.parse_args(argv)
    if opts.sample_rtt:
        return _sample_rtt_main(
            opts.sample_rtt, opts.node_id, opts.seconds
        )
    if opts.slice_nodes >= opts.nodes:
        ap.error("--slice-nodes must be < --nodes")
    _raise_fd_limit()
    import tempfile

    if opts.session_dir is None:
        opts.session_dir = tempfile.mkdtemp(prefix="ray_tpu_scale_sim_")
    os.makedirs(opts.session_dir, exist_ok=True)
    doc = asyncio.run(run_sim(opts))
    if opts.out:
        with open(opts.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    print(json.dumps({"name": "head_scale_ok",
                      "value": 1 if doc.get("ok") else 0,
                      "unit": "bool"}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
