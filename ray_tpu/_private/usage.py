"""Usage reporting (reference: python/ray/_private/usage/usage_lib.py —
cluster metadata + feature-usage tags collected at runtime, written to
the session dir, and POSTed to a collector unless disabled).

TPU-native stance: reporting is **opt-in** (the reference is opt-out):
nothing leaves the machine unless RAY_TPU_USAGE_REPORT_URL is set. The
record is always collected locally though — `usage_stats()` feeds the
dashboard/state API, and the session-dir file gives operators the same
artifact the reference writes (usage_stats.json).
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time

_lib_usages: set[str] = set()


def record_library_usage(name: str) -> None:
    """Tag a subsystem as used this session (reference:
    record_library_usage — serve/train/tune/data call it on import)."""
    _lib_usages.add(name)


def usage_stats() -> dict:
    """The full usage record (schema_version'd like the reference)."""
    from ray_tpu.version import __version__

    record = {
        "schema_version": "0.1",
        "ray_tpu_version": __version__,
        "python_version": sys.version.split()[0],
        "os": platform.system().lower(),
        "collected_at": time.time(),
        "libraries": sorted(_lib_usages),
    }
    # Device info is recorded ONLY if this process already created a jax
    # backend. Probing otherwise would initialize libtpu here and take its
    # exclusive chip lock — fatal when called from the head daemon, which
    # must leave the chips for the workers (accelerators/tpu.py detects
    # chips without a backend for the same reason).
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        record["jax_version"] = jax_mod.__version__
        try:
            from jax._src import xla_bridge

            if xla_bridge._backends:  # backend exists; probing is free
                record["backend"] = jax_mod.default_backend()
                record["device_count"] = jax_mod.device_count()
                record["device_kind"] = jax_mod.devices()[0].device_kind
        # tpulint: allow(broad-except reason=jax-internals probe for optional usage fields; any layout shift just omits them from the record)
        except Exception:  # noqa: BLE001 - internal layout may shift
            pass
    try:
        from ray_tpu import api as core_api

        rt = core_api._runtime
        if rt.ready:
            table = rt.run(rt.core.head.call("node_table"), 5)
            record["cluster_nodes"] = len(table)
            totals: dict[str, float] = {}
            for n in table.values():
                for k, v in n.get("resources", {}).items():
                    totals[k] = totals.get(k, 0) + v
            record["cluster_resources"] = totals
    # tpulint: allow(broad-except reason=cluster-shape probe for optional usage fields; a process without a cluster simply reports none)
    except Exception:  # noqa: BLE001 - no cluster is fine
        pass
    return record


def write_usage_file(session_dir: str) -> str:
    """Drop usage_stats.json in the session dir (local artifact only)."""
    path = os.path.join(session_dir, "usage_stats.json")
    with open(path, "w") as f:
        json.dump(usage_stats(), f, indent=2)
    return path


def report_if_enabled(timeout: float = 5.0) -> bool:
    """POST the record to RAY_TPU_USAGE_REPORT_URL. OPT-IN: with the
    env var unset (the default) this is a no-op and nothing ever
    leaves the machine. Returns whether a report was sent."""
    # tpulint: allow(TPU703 reason=opt-in telemetry gate is deliberately env-only — unset means provably nothing leaves the machine, no config layer can flip it)
    url = os.environ.get("RAY_TPU_USAGE_REPORT_URL", "")
    if not url:
        return False
    import urllib.request

    data = json.dumps(usage_stats()).encode()
    req = urllib.request.Request(
        url,
        data=data,
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout):
            return True
    except OSError:
        return False  # best-effort: never fail the caller
