"""Runtime concurrency sanitizer: instrumented locks.

The static side (``ray_tpu/_private/lint``) catches lock-order cycles
it can SEE; this module is the dynamic backstop for the ones it can't
(locks passed through data structures, order established across
callbacks). An ``InstrumentedLock`` records, per thread, the stack of
instrumented locks held at each acquisition and feeds a global
lock-order graph:

- acquiring B while holding A adds edge A→B; if B→…→A already exists,
  the acquisition raises :class:`LockOrderViolation` NAMING the cycle —
  at the acquisition that would deadlock, not minutes later when two
  threads actually interleave.
- releasing a lock held longer than ``RAY_TPU_SANITIZE_HOLD_MS``
  (default 100) logs a warning with the hold duration — the
  blocking-while-holding shape TPU201 flags statically.

Opt-in: ``RAY_TPU_SANITIZE=1`` makes :func:`maybe_lock` /
:func:`maybe_rlock` hand out instrumented locks, and
:func:`install` monkeypatches ``threading.Lock``/``RLock`` so locks
allocated by ray_tpu code during the install window are instrumented
(allocation-site filtered: third-party/stdlib locks are left alone —
their internal ordering conventions are not ours to police).
``tests/conftest.py`` installs it for the chaos / fault-tolerance
modules.
"""

from __future__ import annotations

import _thread
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

_DEFAULT_HOLD_MS = 100.0


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here can deadlock: the lock-order graph
    already contains a path back to a lock this thread holds."""

    def __init__(self, cycle: list[str], holder_hint: str = ""):
        self.cycle = cycle
        msg = " -> ".join(cycle)
        if holder_hint:
            msg += f" ({holder_hint})"
        super().__init__(f"lock-order cycle: {msg}")


def enabled() -> bool:
    return os.environ.get("RAY_TPU_SANITIZE", "") == "1"


def _hold_threshold_s() -> float:
    try:
        return float(
            os.environ.get("RAY_TPU_SANITIZE_HOLD_MS", _DEFAULT_HOLD_MS)
        ) / 1000.0
    except ValueError:
        return _DEFAULT_HOLD_MS / 1000.0


class _OrderGraph:
    """Global lock-order graph. Guarded by a RAW lock (allocated via
    _thread, never instrumented: the sanitizer must not sanitize its
    own plumbing into infinite recursion)."""

    def __init__(self):
        self._guard = _thread.allocate_lock()
        self._edges: dict[int, set[int]] = {}
        self._names: dict[int, str] = {}
        self.cycles_detected = 0
        self.long_holds = 0

    def reset(self):
        with self._guard:
            self._edges.clear()
            self._names.clear()
            self.cycles_detected = 0
            self.long_holds = 0

    def check_and_add(self, held_id: int, held_name: str,
                      new_id: int, new_name: str) -> list[str] | None:
        """Add edge held→new; return the cycle as names if one forms."""
        with self._guard:
            self._names[held_id] = held_name
            self._names[new_id] = new_name
            # Path new → … → held already present means held→new closes
            # a cycle.
            path = self._find_path(new_id, held_id)
            if path is not None:
                self.cycles_detected += 1
                names = [self._names.get(n, f"lock@{n:#x}")
                         for n in [held_id] + path]
                return names
            self._edges.setdefault(held_id, set()).add(new_id)
            return None

    def _find_path(self, src: int, dst: int) -> list[int] | None:
        if src == dst:
            return [src]
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


_graph = _OrderGraph()
_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class InstrumentedLock:
    """Drop-in for ``threading.Lock``/``RLock`` with order tracking."""

    def __init__(self, name: str | None = None, reentrant: bool = False,
                 hold_threshold_s: float | None = None):
        # The ORIGINAL factories: threading.Lock/RLock may be patched
        # to _patched_lock while install() is active — building the
        # inner lock through them would recurse.
        self._inner = _ORIG_RLOCK() if reentrant else _ORIG_LOCK()
        self.name = name or f"lock@{id(self):#x}"
        self.reentrant = reentrant
        self._hold_threshold_s = (
            hold_threshold_s if hold_threshold_s is not None
            else _hold_threshold_s()
        )
        # owner bookkeeping for reentrancy / hold timing
        self._acquired_at: dict[int, float] = {}
        self._depth: dict[int, int] = {}

    # ------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = _thread.get_ident()
        stack = _held_stack()
        if self.reentrant and self._depth.get(me, 0) > 0:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._depth[me] += 1
            return got
        for held in stack:
            if held is self:
                continue
            cycle = _graph.check_and_add(
                id(held), held.name, id(self), self.name)
            if cycle is not None:
                raise LockOrderViolation(
                    cycle,
                    holder_hint=(
                        f"thread {threading.current_thread().name} "
                        f"holds {held.name}, wants {self.name}"
                    ),
                )
        got = self._inner.acquire(blocking, timeout)
        if got:
            stack.append(self)
            self._acquired_at[me] = time.monotonic()
            if self.reentrant:
                self._depth[me] = 1
        return got

    def release(self):
        me = _thread.get_ident()
        if self.reentrant and self._depth.get(me, 0) > 1:
            self._depth[me] -= 1
            self._inner.release()
            return
        t0 = self._acquired_at.pop(me, None)
        self._depth.pop(me, None)
        stack = _held_stack()
        if self in stack:
            stack.remove(self)
        self._inner.release()
        if t0 is not None:
            held_s = time.monotonic() - t0
            if held_s > self._hold_threshold_s:
                _graph.long_holds += 1
                logger.warning(
                    "sanitizer: %s held for %.0f ms (> %.0f ms) by "
                    "thread %s — was something blocking inside the "
                    "critical section?",
                    self.name, held_s * 1e3,
                    self._hold_threshold_s * 1e3,
                    threading.current_thread().name,
                )

    # ------------------------------------------------------ protocol
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(
            self._inner, "locked") else False

    def __repr__(self):
        kind = "RLock" if self.reentrant else "Lock"
        return f"<InstrumentedLock {kind} {self.name!r}>"


_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_install_count = 0


def maybe_lock(name: str | None = None):
    """threading.Lock(), instrumented when RAY_TPU_SANITIZE=1."""
    if enabled() or _install_count:
        return InstrumentedLock(name=name)
    return _ORIG_LOCK()


def maybe_rlock(name: str | None = None):
    if enabled() or _install_count:
        return InstrumentedLock(name=name, reentrant=True)
    return _ORIG_RLOCK()


def _caller_module(depth: int = 2) -> str:
    import sys
    try:
        frame = sys._getframe(depth)
        return frame.f_globals.get("__name__", "")
    except ValueError:
        return ""


def _patched_lock():
    mod = _caller_module()
    if mod.startswith("ray_tpu") or mod.startswith("test"):
        return InstrumentedLock(name=f"{mod}.Lock@{_site_tag()}")
    return _ORIG_LOCK()


def _patched_rlock():
    mod = _caller_module()
    if mod.startswith("ray_tpu") or mod.startswith("test"):
        return InstrumentedLock(
            name=f"{mod}.RLock@{_site_tag()}", reentrant=True)
    return _ORIG_RLOCK()


def _site_tag() -> str:
    import sys
    try:
        frame = sys._getframe(3)
        return f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
    except ValueError:
        return "?"


def install():
    """Monkeypatch threading.Lock/RLock: locks allocated by ray_tpu /
    tests code while installed come back instrumented. Reference-
    counted so nested installs (fixture + explicit) compose."""
    global _install_count
    _install_count += 1
    if _install_count == 1:
        threading.Lock = _patched_lock
        threading.RLock = _patched_rlock


def uninstall():
    global _install_count
    if _install_count == 0:
        return
    _install_count -= 1
    if _install_count == 0:
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK


def reset():
    """Clear the global order graph (test isolation: one module's lock
    order must not poison the next's)."""
    _graph.reset()


def stats() -> dict:
    return {
        "cycles_detected": _graph.cycles_detected,
        "long_holds": _graph.long_holds,
        "edges": sum(len(v) for v in _graph._edges.values()),
    }
