"""Runtime concurrency sanitizer: instrumented locks.

The static side (``ray_tpu/_private/lint``) catches lock-order cycles
it can SEE; this module is the dynamic backstop for the ones it can't
(locks passed through data structures, order established across
callbacks). An ``InstrumentedLock`` records, per thread, the stack of
instrumented locks held at each acquisition and feeds a global
lock-order graph:

- acquiring B while holding A adds edge A→B; if B→…→A already exists,
  the acquisition raises :class:`LockOrderViolation` NAMING the cycle —
  at the acquisition that would deadlock, not minutes later when two
  threads actually interleave.
- releasing a lock held longer than ``RAY_TPU_SANITIZE_HOLD_MS``
  (default 100) logs a warning with the hold duration — the
  blocking-while-holding shape TPU201 flags statically.

Runtime twins of the v2 flow-sensitive lint passes:

- **async-lock awareness (TPU203's twin)**: :class:`InstrumentedLock`
  warns when a *blocking* acquire happens on a thread that is running
  an asyncio event loop (the loop freezes for every coroutine), and
  :class:`InstrumentedAsyncLock` puts ``asyncio.Lock`` acquisitions
  into the SAME order graph as the threading locks — a sync/async
  lock inversion is still an inversion.
- **leak reporter (TPU104/TPU404's twin)**: :func:`watch_work` /
  :func:`watch_registration` attach a ``weakref.finalize`` to a
  :class:`~ray_tpu.collective.types.CollectiveWork` or a
  ``memory.Registration``; if the object is garbage-collected
  un-``wait()``ed / un-``close()``d, a warning names what was
  dropped. The static passes catch the paths they can see — this
  catches the handles that escaped into data structures.
- **jit compile watch (TPU603's twin)**: :func:`install_jax_watch`
  monkeypatches ``jax.jit`` so every compiled callable ray_tpu/test
  code creates is wrapped by :func:`watch_jit`: per-call abstract
  argument signatures (shape/dtype for arrays, type for traced
  scalars, VALUE for statics) are tracked, and a NEW signature after
  ``RAY_TPU_SANITIZE_COMPILE_GRACE`` steady-state calls warns naming
  the argument that changed and increments
  ``ray_tpu_sanitize_recompiles_total{fn}`` — steady-state
  recompilation is a 1000x step-time hiccup the call site never sees.
- **host-sync tracer (TPU601's twin)**: the same install patches
  ``jax.block_until_ready`` / ``jax.device_get`` to record wall-clock
  sync intervals; ``ray_tpu/train/telemetry.py`` drains them at step
  close and attributes the portion inside compute-phase spans as a
  ``host_sync_exposed_s`` step-span attr, next to PR-9's
  comm-exposure attribution.

Opt-in: ``RAY_TPU_SANITIZE=1`` makes :func:`maybe_lock` /
:func:`maybe_rlock` / :func:`maybe_async_lock` hand out instrumented
locks, enables the leak watchers, and :func:`install` monkeypatches
``threading.Lock``/``RLock`` so locks allocated by ray_tpu code during
the install window are instrumented (allocation-site filtered:
third-party/stdlib locks are left alone — their internal ordering
conventions are not ours to police). ``RAY_TPU_SANITIZE_LEAKS=1``
enables just the leak watchers. ``tests/conftest.py`` installs the
lock side for the chaos / fault-tolerance modules.
"""

from __future__ import annotations

import _thread
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

_DEFAULT_HOLD_MS = 100.0


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here can deadlock: the lock-order graph
    already contains a path back to a lock this thread holds."""

    def __init__(self, cycle: list[str], holder_hint: str = ""):
        self.cycle = cycle
        msg = " -> ".join(cycle)
        if holder_hint:
            msg += f" ({holder_hint})"
        super().__init__(f"lock-order cycle: {msg}")


def enabled() -> bool:
    # tpulint: allow(TPU703 reason=the sanitizer is the independent backstop — its gate must not depend on the config machinery it may be diagnosing)
    return os.environ.get("RAY_TPU_SANITIZE", "") == "1"


def _hold_threshold_s() -> float:
    try:
        return float(
            # tpulint: allow(TPU703 reason=sanitizer knobs stay env-only so the backstop works even when config loading is itself broken)
            os.environ.get("RAY_TPU_SANITIZE_HOLD_MS", _DEFAULT_HOLD_MS)
        ) / 1000.0
    except ValueError:
        return _DEFAULT_HOLD_MS / 1000.0


class _OrderGraph:
    """Global lock-order graph. Guarded by a RAW lock (allocated via
    _thread, never instrumented: the sanitizer must not sanitize its
    own plumbing into infinite recursion)."""

    def __init__(self):
        self._guard = _thread.allocate_lock()
        self._edges: dict[int, set[int]] = {}
        self._names: dict[int, str] = {}
        self.cycles_detected = 0
        self.long_holds = 0
        self.loop_thread_acquires = 0
        self.work_leaks = 0
        self.registration_leaks = 0
        self.recompiles = 0
        self.host_syncs = 0
        self.rpc_contract_misses = 0

    def reset(self):
        with self._guard:
            self._edges.clear()
            self._names.clear()
            self.cycles_detected = 0
            self.long_holds = 0
            self.loop_thread_acquires = 0
            self.work_leaks = 0
            self.registration_leaks = 0
            self.recompiles = 0
            self.host_syncs = 0
            self.rpc_contract_misses = 0

    def check_and_add(self, held_id: int, held_name: str,
                      new_id: int, new_name: str) -> list[str] | None:
        """Add edge held→new; return the cycle as names if one forms."""
        with self._guard:
            self._names[held_id] = held_name
            self._names[new_id] = new_name
            # Path new → … → held already present means held→new closes
            # a cycle.
            path = self._find_path(new_id, held_id)
            if path is not None:
                self.cycles_detected += 1
                names = [self._names.get(n, f"lock@{n:#x}")
                         for n in [held_id] + path]
                return names
            self._edges.setdefault(held_id, set()).add(new_id)
            return None

    def _find_path(self, src: int, dst: int) -> list[int] | None:
        if src == dst:
            return [src]
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


_graph = _OrderGraph()
_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class InstrumentedLock:
    """Drop-in for ``threading.Lock``/``RLock`` with order tracking."""

    def __init__(self, name: str | None = None, reentrant: bool = False,
                 hold_threshold_s: float | None = None):
        # The ORIGINAL factories: threading.Lock/RLock may be patched
        # to _patched_lock while install() is active — building the
        # inner lock through them would recurse.
        self._inner = _ORIG_RLOCK() if reentrant else _ORIG_LOCK()
        self.name = name or f"lock@{id(self):#x}"
        self.reentrant = reentrant
        self._hold_threshold_s = (
            hold_threshold_s if hold_threshold_s is not None
            else _hold_threshold_s()
        )
        # owner bookkeeping for reentrancy / hold timing
        self._acquired_at: dict[int, float] = {}
        self._depth: dict[int, int] = {}

    # ------------------------------------------------------- acquire
    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = _thread.get_ident()
        stack = _held_stack()
        if blocking and _on_event_loop_thread():
            # TPU203's runtime twin: a blocking lock acquire on the
            # loop thread freezes every coroutine until it's granted.
            _graph.loop_thread_acquires += 1
            logger.warning(
                "sanitizer: blocking acquire of %s on an event-loop "
                "thread (%s) — the loop (and every coroutine on it) "
                "stalls until the lock is granted; use asyncio.Lock "
                "or run the critical section in an executor",
                self.name, threading.current_thread().name,
            )
        if self.reentrant and self._depth.get(me, 0) > 0:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._depth[me] += 1
            return got
        for held in stack:
            if held is self:
                continue
            cycle = _graph.check_and_add(
                id(held), held.name, id(self), self.name)
            if cycle is not None:
                raise LockOrderViolation(
                    cycle,
                    holder_hint=(
                        f"thread {threading.current_thread().name} "
                        f"holds {held.name}, wants {self.name}"
                    ),
                )
        got = self._inner.acquire(blocking, timeout)
        if got:
            stack.append(self)
            self._acquired_at[me] = time.monotonic()
            if self.reentrant:
                self._depth[me] = 1
        return got

    def release(self):
        me = _thread.get_ident()
        if self.reentrant and self._depth.get(me, 0) > 1:
            self._depth[me] -= 1
            self._inner.release()
            return
        t0 = self._acquired_at.pop(me, None)
        self._depth.pop(me, None)
        stack = _held_stack()
        if self in stack:
            stack.remove(self)
        self._inner.release()
        if t0 is not None:
            held_s = time.monotonic() - t0
            if held_s > self._hold_threshold_s:
                _graph.long_holds += 1
                logger.warning(
                    "sanitizer: %s held for %.0f ms (> %.0f ms) by "
                    "thread %s — was something blocking inside the "
                    "critical section?",
                    self.name, held_s * 1e3,
                    self._hold_threshold_s * 1e3,
                    threading.current_thread().name,
                )

    # ------------------------------------------------------ protocol
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(
            self._inner, "locked") else False

    def __repr__(self):
        kind = "RLock" if self.reentrant else "Lock"
        return f"<InstrumentedLock {kind} {self.name!r}>"


_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_install_count = 0


def _on_event_loop_thread() -> bool:
    try:
        import asyncio
        return asyncio.events._get_running_loop() is not None
    # tpulint: allow(broad-except reason=the loop probe is best-effort diagnostics; any asyncio internals change must degrade to "not on a loop", never break lock acquisition)
    except Exception:  # noqa: BLE001
        return False


class InstrumentedAsyncLock:
    """``asyncio.Lock`` twin of :class:`InstrumentedLock`: acquisitions
    join the SAME global order graph (checked against both the locks
    this *task* holds and the threading locks this *thread* holds — a
    sync/async inversion deadlocks just as hard), and holds longer
    than the threshold warn on release."""

    def __init__(self, name: str | None = None,
                 hold_threshold_s: float | None = None):
        import asyncio

        self._inner = asyncio.Lock()
        self.name = name or f"alock@{id(self):#x}"
        self._hold_threshold_s = (
            hold_threshold_s if hold_threshold_s is not None
            else _hold_threshold_s()
        )
        self._acquired_at: float | None = None

    def _check_order(self):
        holders = list(_task_held_stack()) + list(_held_stack())
        for held in holders:
            if held is self:
                continue
            cycle = _graph.check_and_add(
                id(held), held.name, id(self), self.name)
            if cycle is not None:
                raise LockOrderViolation(
                    cycle,
                    holder_hint=(
                        f"task holds {held.name}, wants {self.name}"
                    ),
                )

    async def acquire(self):
        self._check_order()
        got = await self._inner.acquire()
        _task_held_stack().append(self)
        self._acquired_at = time.monotonic()
        return got

    def release(self):
        stack = _task_held_stack()
        if self in stack:
            stack.remove(self)
        t0, self._acquired_at = self._acquired_at, None
        self._inner.release()
        if t0 is not None:
            held_s = time.monotonic() - t0
            if held_s > self._hold_threshold_s:
                _graph.long_holds += 1
                logger.warning(
                    "sanitizer: %s held for %.0f ms (> %.0f ms) — was "
                    "something blocking inside the async critical "
                    "section?",
                    self.name, held_s * 1e3,
                    self._hold_threshold_s * 1e3,
                )

    def locked(self):
        return self._inner.locked()

    async def __aenter__(self):
        await self.acquire()
        return self

    async def __aexit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedAsyncLock {self.name!r}>"


# Per-task held stacks for async locks; keyed by task id, pruned on
# release (a finished task's entry dies with its last release — tasks
# that leak a lock leak one list entry, which the leak warning already
# shouts about).
_task_held: dict[int, list] = {}


def _task_held_stack() -> list:
    try:
        import asyncio
        task = asyncio.current_task()
    # tpulint: allow(broad-except reason=outside a running loop there is no task; order checks then cover only thread-held locks)
    except Exception:  # noqa: BLE001
        task = None
    if task is None:
        return _held_stack()
    key = id(task)
    stack = _task_held.get(key)
    if stack is None:
        stack = _task_held[key] = []
    elif not stack:
        # opportunistic prune of empty entries from finished tasks
        for k in [k for k, v in _task_held.items() if not v and k != key]:
            del _task_held[k]
    return stack


# --------------------------------------------------------- leak reporter
def leaks_enabled() -> bool:
    # tpulint: allow(TPU703 reason=sanitizer knobs stay env-only so the backstop works even when config loading is itself broken)
    return enabled() or os.environ.get(
        "RAY_TPU_SANITIZE_LEAKS", "") == "1"


def watch_work(handle) -> None:
    """Warn if ``handle`` (a CollectiveWork) is GC'd before any
    ``wait()`` reached a terminal outcome: the dispatched collective's
    result — and any typed fault — was silently dropped. Wired into
    ``CollectiveWork.__init__`` when :func:`leaks_enabled`."""
    import weakref

    box = {
        "closed": False,
        "desc": f"{handle.verb or 'op'} group="
                f"{handle.group_name or '?'}",
    }
    handle._leak_box = box
    weakref.finalize(handle, _report_work_leak, box)


def _report_work_leak(box):
    if box["closed"]:
        return
    _graph.work_leaks += 1
    logger.warning(
        "sanitizer: CollectiveWork (%s) garbage-collected without a "
        "completed wait() — the dispatched collective's result and "
        "typed errors were silently dropped (TPU104's runtime twin)",
        box["desc"],
    )


def watch_registration(reg) -> None:
    """Warn if a memory-ledger Registration is GC'd still open: the
    byte claim silently outlives its subsystem and the device-memory
    ledger over-reports. Wired into ``memory.track()`` when
    :func:`leaks_enabled`."""
    import weakref

    box = {"closed": False, "desc": f"{reg.tag} kind={reg.kind}"}
    reg._leak_box = box
    weakref.finalize(reg, _report_registration_leak, box)


def _report_registration_leak(box):
    if box["closed"]:
        return
    _graph.registration_leaks += 1
    logger.warning(
        "sanitizer: memory Registration (%s) garbage-collected while "
        "still open — the byte claim was never close()d and the "
        "device-memory ledger over-reports (TPU404's runtime twin)",
        box["desc"],
    )


def maybe_lock(name: str | None = None):
    """threading.Lock(), instrumented when RAY_TPU_SANITIZE=1."""
    if enabled() or _install_count:
        return InstrumentedLock(name=name)
    return _ORIG_LOCK()


def maybe_rlock(name: str | None = None):
    if enabled() or _install_count:
        return InstrumentedLock(name=name, reentrant=True)
    return _ORIG_RLOCK()


def maybe_async_lock(name: str | None = None):
    """asyncio.Lock(), instrumented when RAY_TPU_SANITIZE=1."""
    if enabled() or _install_count:
        return InstrumentedAsyncLock(name=name)
    import asyncio

    return asyncio.Lock()


def _caller_module(depth: int = 2) -> str:
    import sys
    try:
        frame = sys._getframe(depth)
        return frame.f_globals.get("__name__", "")
    except ValueError:
        return ""


def _patched_lock():
    mod = _caller_module()
    if mod.startswith("ray_tpu") or mod.startswith("test"):
        return InstrumentedLock(name=f"{mod}.Lock@{_site_tag()}")
    return _ORIG_LOCK()


def _patched_rlock():
    mod = _caller_module()
    if mod.startswith("ray_tpu") or mod.startswith("test"):
        return InstrumentedLock(
            name=f"{mod}.RLock@{_site_tag()}", reentrant=True)
    return _ORIG_RLOCK()


def _site_tag() -> str:
    import sys
    try:
        frame = sys._getframe(3)
        return f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
    except ValueError:
        return "?"


def install():
    """Monkeypatch threading.Lock/RLock: locks allocated by ray_tpu /
    tests code while installed come back instrumented. Reference-
    counted so nested installs (fixture + explicit) compose."""
    global _install_count
    _install_count += 1
    if _install_count == 1:
        threading.Lock = _patched_lock
        threading.RLock = _patched_rlock


def uninstall():
    global _install_count
    if _install_count == 0:
        return
    _install_count -= 1
    if _install_count == 0:
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK


# ------------------------------------------------- jit compile watch
_COMPILE_GRACE_DEFAULT = 3
_RECOMPILES_TOTAL = None


def compile_grace() -> int:
    """Steady-state call count after which a new signature is a
    recompile WARNING rather than expected warm-up (shape buckets,
    first batch, eval shapes all compile early by design)."""
    try:
        # tpulint: allow(TPU703 reason=sanitizer knobs stay env-only so the backstop works even when config loading is itself broken)
        return int(os.environ.get(
            "RAY_TPU_SANITIZE_COMPILE_GRACE", _COMPILE_GRACE_DEFAULT))
    except ValueError:
        return _COMPILE_GRACE_DEFAULT


def _recompile_counter():
    global _RECOMPILES_TOTAL
    if _RECOMPILES_TOTAL is None:
        from ray_tpu.util.metrics import Counter

        # tpulint: allow(TPU401 reason=module-level None-guarded singleton - sanitize imports before the metrics registry on every process boot path, so the ctor is deferred to first recompile; it runs at most once)
        _RECOMPILES_TOTAL = Counter(
            "ray_tpu_sanitize_recompiles_total",
            "jit recompilations observed after the steady-state grace "
            "(RAY_TPU_SANITIZE_COMPILE_GRACE) by the sanitizer's "
            "compile watch",
            tag_keys=("fn",),
        )
    return _RECOMPILES_TOTAL


def _sig_one(x, static: bool):
    """Abstract signature of one argument: what the jit cache keys on.
    Arrays by (shape, dtype); pytree containers structurally; traced
    Python scalars by TYPE (weak-type caching is value-independent);
    statics by VALUE (that is exactly what retraces)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if isinstance(x, (list, tuple)):
        return (type(x).__name__,
                tuple(_sig_one(v, static) for v in x))
    if isinstance(x, dict):
        return ("dict", tuple(
            (str(k), _sig_one(v, static))
            for k, v in sorted(x.items(), key=lambda kv: str(kv[0]))))
    if static:
        try:
            return ("static", repr(x)[:120])
        # tpulint: allow(broad-except reason=a static with a throwing repr must degrade to an opaque token, never break the watched call)
        except Exception:  # noqa: BLE001
            return ("static", f"<unreprable {type(x).__name__}>")
    return ("py", type(x).__name__)


def _signature(args, kwargs, static_argnums, static_argnames):
    parts = []
    for i, a in enumerate(args):
        parts.append((str(i), _sig_one(a, i in static_argnums)))
    for k in sorted(kwargs):
        parts.append((k, _sig_one(kwargs[k], k in static_argnames)))
    return tuple(parts)


def _sig_diff(old, new) -> str:
    """Human-readable 'which argument changed' between two signatures."""
    if old is None:
        return "first tracked signature"
    old_map = dict(old)
    changes = []
    for key, val in new:
        prev = old_map.get(key)
        if prev != val:
            changes.append(f"arg {key}: {prev} -> {val}")
    for key in old_map:
        if key not in dict(new):
            changes.append(f"arg {key} removed")
    return "; ".join(changes) or "argument structure changed"


class WatchedJit:
    """Wrapper around a compiled callable that tracks abstract argument
    signatures and warns on a NEW one after the steady-state grace —
    the jit cache grew when the hot loop should be cache-stable."""

    __slots__ = ("_jitted", "name", "_static_argnums",
                 "_static_argnames", "_seen", "_calls", "_last_sig",
                 "__weakref__")

    def __init__(self, jitted, name: str,
                 static_argnums=(), static_argnames=()):
        self._jitted = jitted
        self.name = name
        self._static_argnums = frozenset(static_argnums)
        self._static_argnames = frozenset(static_argnames)
        self._seen: set = set()
        self._calls = 0
        self._last_sig = None

    def __call__(self, *args, **kwargs):
        sig = _signature(args, kwargs, self._static_argnums,
                         self._static_argnames)
        self._calls += 1
        known = sig in self._seen
        if not known:
            if self._seen and self._calls > compile_grace():
                _graph.recompiles += 1
                diff = _sig_diff(self._last_sig, sig)
                _recompile_counter().inc(tags={"fn": self.name})
                logger.warning(
                    "sanitizer: jitted %s RECOMPILED at call %d "
                    "(signature #%d, after %d steady-state calls): %s "
                    "— a steady-state cache miss costs seconds per "
                    "hit; bucket the varying argument or mark it "
                    "traced (TPU603's runtime twin)",
                    self.name, self._calls, len(self._seen) + 1,
                    compile_grace(), diff,
                )
            self._seen.add(sig)
        self._last_sig = sig
        # Cache-EVICTION recompiles hide from signature tracking: the
        # signature was seen, but XLA's compilation cache dropped the
        # executable and the call silently recompiled. jax.monitoring's
        # backend-compile duration event fires exactly then (and not on
        # cache hits), so a counter advance during an already-seen call
        # past the grace is an eviction recompile.
        before = _backend_compiles
        try:
            return self._jitted(*args, **kwargs)
        finally:
            if (known and self._calls > compile_grace()
                    and _backend_compiles > before):
                _graph.recompiles += 1
                _recompile_counter().inc(tags={"fn": self.name})
                logger.warning(
                    "sanitizer: jitted %s RECOMPILED at call %d for an "
                    "ALREADY-SEEN signature — the XLA compilation "
                    "cache evicted it (cache thrash, not a new shape); "
                    "raise the cache budget or reduce live programs",
                    self.name, self._calls,
                )

    def __getattr__(self, item):
        return getattr(self._jitted, item)

    def __repr__(self):
        return f"<WatchedJit {self.name!r} signatures={len(self._seen)}>"


def _norm_argnums(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, int):
        return (v,)
    return tuple(v)


def _norm_argnames(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, str):
        return (v,)
    return tuple(v)


def watch_jit(jitted, name: str | None = None,
              static_argnums=None, static_argnames=None) -> WatchedJit:
    """Wrap an already-compiled callable in the compile watch."""
    if name is None:
        name = getattr(jitted, "__qualname__", None) or getattr(
            jitted, "__name__", None) or repr(jitted)
    return WatchedJit(
        jitted, name,
        static_argnums=_norm_argnums(static_argnums),
        static_argnames=_norm_argnames(static_argnames),
    )


_jax_watch_count = 0
_ORIG_JAX_JIT = None
_ORIG_BLOCK_UNTIL_READY = None
_ORIG_DEVICE_GET = None
# Backend-compile monitor: jax emits this duration event on every real
# backend compilation (and NOT on jit-cache hits), which is what lets
# the compile watch see cache-eviction recompiles of already-seen
# signatures. The literal is the fallback for jax versions that don't
# export BACKEND_COMPILE_EVENT from jax._src.dispatch.
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_backend_compiles = 0
_compile_monitor_registered = False


def _register_compile_monitor() -> None:
    """Register the jax.monitoring listener once per process.
    jax.monitoring has no unregister, so the callback itself gates on
    the watch refcount instead of being torn down."""
    global _compile_monitor_registered
    if _compile_monitor_registered:
        return
    try:
        from jax import monitoring
        try:
            from jax._src import dispatch as _dispatch

            event = getattr(
                _dispatch, "BACKEND_COMPILE_EVENT",
                _BACKEND_COMPILE_EVENT,
            )
        except ImportError:
            event = _BACKEND_COMPILE_EVENT

        def _on_compile_duration(evt, _duration, **_kw):
            global _backend_compiles
            if evt == event and _jax_watch_count > 0:
                _backend_compiles += 1

        monitoring.register_event_duration_secs_listener(
            _on_compile_duration
        )
    # tpulint: allow(broad-except reason=the monitoring hook is best-effort hardening of the compile watch; any jax-internals drift degrades to signature-only tracking, never breaks install)
    except Exception:  # noqa: BLE001
        return
    _compile_monitor_registered = True
# Bounded ring of completed host-sync wall intervals, drained by the
# train-step telemetry (host_sync_exposed_s attribution).
_SYNC_RING_MAX = 4096
_sync_guard = _thread.allocate_lock()
_sync_intervals: list[tuple[float, float]] = []


def _patched_jax_jit(fun=None, **kwargs):
    import functools

    if fun is None:
        return functools.partial(_patched_jax_jit, **kwargs)
    jitted = _ORIG_JAX_JIT(fun, **kwargs)
    mod = _caller_module()
    if not (mod.startswith("ray_tpu") or mod.startswith("test")):
        return jitted
    name = getattr(fun, "__qualname__", None) or getattr(
        fun, "__name__", None) or f"{mod}.<jit>"
    return WatchedJit(
        jitted, f"{mod}.{name}",
        static_argnums=_norm_argnums(kwargs.get("static_argnums")),
        static_argnames=_norm_argnames(kwargs.get("static_argnames")),
    )


def _record_sync(t0: float, t1: float) -> None:
    _graph.host_syncs += 1
    with _sync_guard:
        _sync_intervals.append((t0, t1))
        if len(_sync_intervals) > _SYNC_RING_MAX:
            del _sync_intervals[: _SYNC_RING_MAX // 2]


def _timed_sync(orig):
    import functools

    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        t0 = time.time()
        try:
            return orig(*args, **kwargs)
        finally:
            _record_sync(t0, time.time())

    return wrapper


def take_host_sync_intervals() -> list[tuple[float, float]]:
    """Drain the recorded block_until_ready/device_get wall intervals
    (the telemetry's step-close attribution consumes these, exactly
    like the flight recorder's op intervals)."""
    with _sync_guard:
        out, _sync_intervals[:] = list(_sync_intervals), []
    return out


def jax_watch_active() -> bool:
    return _jax_watch_count > 0


def install_jax_watch():
    """Monkeypatch ``jax.jit`` (compile watch) and
    ``jax.block_until_ready``/``jax.device_get`` (host-sync tracer).
    Reference-counted like :func:`install`; a missing jax degrades to
    a no-op so non-accelerator processes can enable RAY_TPU_SANITIZE=1
    unconditionally."""
    global _jax_watch_count, _ORIG_JAX_JIT
    global _ORIG_BLOCK_UNTIL_READY, _ORIG_DEVICE_GET
    try:
        import jax
    except ImportError:
        return
    _jax_watch_count += 1
    if _jax_watch_count == 1:
        _register_compile_monitor()
        _ORIG_JAX_JIT = jax.jit
        _ORIG_BLOCK_UNTIL_READY = jax.block_until_ready
        _ORIG_DEVICE_GET = jax.device_get
        jax.jit = _patched_jax_jit
        jax.block_until_ready = _timed_sync(_ORIG_BLOCK_UNTIL_READY)
        jax.device_get = _timed_sync(_ORIG_DEVICE_GET)


def uninstall_jax_watch():
    global _jax_watch_count
    if _jax_watch_count == 0:
        return
    try:
        import jax
    except ImportError:  # pragma: no cover - install was a no-op too
        _jax_watch_count = max(0, _jax_watch_count - 1)
        return
    _jax_watch_count -= 1
    if _jax_watch_count == 0:
        jax.jit = _ORIG_JAX_JIT
        jax.block_until_ready = _ORIG_BLOCK_UNTIL_READY
        jax.device_get = _ORIG_DEVICE_GET


def maybe_install_jax_watch():
    """Install the jit-discipline twins when RAY_TPU_SANITIZE=1 — the
    train worker calls this once at setup."""
    if enabled():
        install_jax_watch()


# ---------------------------------------------- rpc contract twin
# Runtime twin of the TPU701 static pass: validate Connection.call
# kwargs against the handler signature table the lint model exports.
# The static pass catches drift it can resolve at analysis time; this
# catches the call sites it can't (f-string methods, kwargs-dict
# splats) — warn-only, because tolerant_kwargs dropping unknown kwargs
# IS the deployed version-skew behavior; the sanitizer's job is to
# make the silence visible.

_contract_table: dict | None = None
_contract_warned: set = set()
_contract_guard = _thread.allocate_lock()
# Mirrors lint.protocol.TRANSPORT_KWARGS without importing the lint
# package at module load.
_TRANSPORT_KWARGS = frozenset({"timeout", "retry"})


def _handler_table() -> dict:
    """Lazily build (once) the package-wide handler signature table.
    Any failure degrades to an empty table — the sanitizer must never
    turn a working RPC path into a crash."""
    global _contract_table
    if _contract_table is None:
        try:
            from ray_tpu._private.lint import protocol
            _contract_table = protocol.handler_signature_table()
        except Exception as e:
            logger.debug("rpc contract table build failed "
                         "(contract checks disabled): %s", e)
            _contract_table = {}
    return _contract_table


def check_rpc_contract(method: str, kw: dict) -> None:
    """Warn (once per method+kind, counting every miss) when a call's
    method or kwargs don't match any known ``_on_<method>`` handler."""
    if ":" in method:
        return  # extension namespaces (col_op:<name>) are dynamic
    table = _handler_table()
    sig = table.get(method)
    problems: list[tuple[str, str]] = []
    if sig is None:
        problems.append((
            "unknown-method",
            f"rpc contract: call({method!r}) matches no _on_{method} "
            "handler anywhere in the package — the server will raise "
            "unknown-method at dispatch",
        ))
    else:
        unknown = set(kw) - sig["params"] - _TRANSPORT_KWARGS
        if unknown and not sig["varkw"]:
            problems.append((
                "unknown-kwarg",
                f"rpc contract: call({method!r}) passes "
                f"{sorted(unknown)} which _on_{method} does not "
                "accept — tolerant_kwargs silently DROPS them on the "
                "server",
            ))
        missing = sig["required"] - set(kw)
        if missing:
            problems.append((
                "missing-required",
                f"rpc contract: call({method!r}) omits required "
                f"parameter(s) {sorted(missing)} of _on_{method} — "
                "the handler raises TypeError at dispatch",
            ))
    if not problems:
        return
    with _contract_guard:
        _graph.rpc_contract_misses += len(problems)
        fresh = [(kind, msg) for kind, msg in problems
                 if (method, kind) not in _contract_warned]
        _contract_warned.update((method, kind) for kind, _ in fresh)
    for _, msg in fresh:
        logger.warning(msg)


def reset():
    """Clear the global order graph (test isolation: one module's lock
    order must not poison the next's)."""
    _graph.reset()
    with _sync_guard:
        _sync_intervals.clear()
    with _contract_guard:
        _contract_warned.clear()


def stats() -> dict:
    return {
        "cycles_detected": _graph.cycles_detected,
        "long_holds": _graph.long_holds,
        "loop_thread_acquires": _graph.loop_thread_acquires,
        "work_leaks": _graph.work_leaks,
        "registration_leaks": _graph.registration_leaks,
        "recompiles": _graph.recompiles,
        "host_syncs": _graph.host_syncs,
        "rpc_contract_misses": _graph.rpc_contract_misses,
        "edges": sum(len(v) for v in _graph._edges.values()),
    }
