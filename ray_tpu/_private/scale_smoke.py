"""Control-plane scale smoke: many nodes x many actors x many PGs on
one host (reference: release/benchmarks/distributed/test_many_actors.py
and test_many_pgs.py — the reference's scalability envelope is released
against 2,000 nodes / 40k actors; this smoke proves the head,
scheduler, resource sync, and journal at the largest scale one machine
supports).

Node daemons are REAL NodeManagers registering over real sockets;
workers use the documented ``WORKER_MODE=inproc`` simulation (see the
config knob) so a thousand actors cost kilobytes each instead of a
Python interpreter each — the control plane cannot tell the difference.

Run:  python -m ray_tpu._private.scale_smoke --nodes 50 --actors 1000 --pgs 50
Emits one JSON row per measurement (name/value/unit).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time


class ScaleActor:
    """Minimal control-plane load: schedulable, pingable, killable."""

    def __init__(self):
        self.n = 0

    def ping(self):
        self.n += 1
        return self.n


def run_scale_smoke(
    n_nodes: int = 50,
    n_actors: int = 1000,
    n_pgs: int = 50,
    journal_dir: str | None = None,
) -> list[dict]:
    os.environ["RAY_TPU_WORKER_MODE"] = "inproc"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import ray_tpu
    from ray_tpu import api as core_api
    from ray_tpu.placement import placement_group, remove_placement_group
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.util import state

    rows: list[dict] = []

    def row(name, value, unit):
        rows.append({"name": name, "value": round(value, 3), "unit": unit})

    sysconf = {}
    journal_path = None
    if journal_dir:
        journal_path = os.path.join(journal_dir, "scale_head.journal")
        sysconf["HEAD_JOURNAL"] = journal_path

    per_node_cpu = max(4.0, (n_actors / n_nodes) * 2)
    ray_tpu.init(num_cpus=int(per_node_cpu), _system_config=sysconf)
    rt = core_api._runtime

    # ---- 1. node registration fan-in -------------------------------
    extra: list[NodeManager] = []
    t0 = time.monotonic()

    async def launch_nodes():
        for i in range(n_nodes - 1):
            node = NodeManager(
                rt.core.head_addr,
                rt.node.store_dir,
                resources={"CPU": per_node_cpu},
                labels={"scale-smoke": str(i)},
            )
            await node.start()
            extra.append(node)

    rt.run(launch_nodes(), timeout=600)
    while len(state.list_nodes()) < n_nodes:
        time.sleep(0.1)
    row(f"scale: register {n_nodes} nodes", time.monotonic() - t0, "s")

    # ---- 2. actor creation throughput + ready latency --------------
    # Creations fire CONCURRENTLY on the runtime loop (the reference's
    # many_actors benchmark is async the same way); create_actor
    # resolves once the actor instance is constructed on its worker,
    # so completion time IS ready latency.
    from ray_tpu.api import ActorHandle

    t0 = time.monotonic()
    ready_at: list[float] = []

    async def create_one():
        actor_id, addr = await rt.core.create_actor(
            ScaleActor, (), {}, resources={"CPU": 0.5}
        )
        ready_at.append(time.monotonic() - t0)
        return ActorHandle(actor_id, addr, "ScaleActor")

    async def create_all():
        import asyncio

        return await asyncio.gather(
            *[create_one() for _ in range(n_actors)]
        )

    actors = rt.run(create_all(), timeout=900)
    total_ready = time.monotonic() - t0
    row(f"scale: {n_actors} actors ready", total_ready, "s")
    row("scale: actor ready throughput", n_actors / total_ready, "actors/s")
    row("scale: actor ready p50", statistics.median(ready_at), "s")
    row(
        "scale: actor ready p99",
        sorted(ready_at)[int(len(ready_at) * 0.99) - 1],
        "s",
    )

    # Scheduling spread: the hybrid policy must not pile every actor
    # on one node. Count against the CURRENT node table — an actor
    # attributed to a node the head transiently dropped during the
    # storm (keepalive starvation) must not inflate the metric.
    table = {n["node_id"] for n in state.list_nodes()}
    hosting = {
        a["node_id"]
        for a in state.list_actors()
        if a["state"] == "ALIVE" and a.get("node_id") in table
    }
    row("scale: nodes hosting actors", len(hosting), "nodes")

    # ---- 3. one call fan-out over every actor ----------------------
    t0 = time.monotonic()
    out = ray_tpu.get([a.ping.remote() for a in actors], timeout=300)
    dt = time.monotonic() - t0
    assert all(v == 1 for v in out)
    row("scale: call fan-out all actors", n_actors / dt, "calls/s")

    # ---- 4. placement groups (2PC prepare/commit) ------------------
    t0 = time.monotonic()
    pgs = [
        placement_group([{"CPU": 0.5}, {"CPU": 0.5}], strategy="PACK")
        for _ in range(n_pgs)
    ]
    assert all(pg.ready() for pg in pgs)
    dt = time.monotonic() - t0
    row(f"scale: {n_pgs} PGs created+ready", dt, "s")
    row("scale: pg throughput", n_pgs / dt, "pgs/s")

    # ---- 5. churn: kill half the actors, create replacements -------
    t0 = time.monotonic()
    for a in actors[: n_actors // 2]:
        ray_tpu.kill(a)

    async def recreate_all():
        import asyncio

        return await asyncio.gather(
            *[create_one() for _ in range(n_actors // 2)]
        )

    replacements = rt.run(recreate_all(), timeout=900)
    ray_tpu.get([a.ping.remote() for a in replacements], timeout=300)
    row(
        "scale: churn half the actors",
        time.monotonic() - t0,
        "s",
    )

    # ---- 6. resource-view convergence after the storm --------------
    expected_used = (n_actors // 2 + len(replacements)) * 0.5 + n_pgs * 1.0
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60:
        nodes = state.list_nodes()
        used = sum(
            n["resources"].get("CPU", 0) - n["available"].get("CPU", 0)
            for n in nodes
        )
        if abs(used - expected_used) < 1.0:
            break
        time.sleep(0.2)
    row("scale: resource view convergence", time.monotonic() - t0, "s")

    # ---- 7. journal growth under churn -----------------------------
    if journal_path and os.path.exists(journal_path):
        row(
            "scale: head journal after churn",
            os.path.getsize(journal_path) / 1e6,
            "MB",
        )

    for pg in pgs:
        remove_placement_group(pg)

    async def stop_nodes():
        for node in extra:
            await node.stop()

    try:
        # Teardown is NOT a measurement: on a loaded host stopping
        # hundreds of simulated nodes can exceed any fixed budget —
        # never let it invalidate the rows already collected
        # (shutdown() below reaps whatever remains).
        rt.run(stop_nodes(), timeout=240)
    # tpulint: allow(broad-except reason=teardown of hundreds of simulated nodes is not a measurement; the incompleteness is printed and shutdown() reaps the rest)
    except Exception as e:  # noqa: BLE001 - best-effort teardown
        print(f"# teardown incomplete (ignored): {e!r}", flush=True)
    finally:
        ray_tpu.shutdown()
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--pgs", type=int, default=50)
    ap.add_argument("--journal-dir", default="/tmp/ray_tpu_scale")
    args = ap.parse_args()
    os.makedirs(args.journal_dir, exist_ok=True)
    rows = run_scale_smoke(
        args.nodes, args.actors, args.pgs, journal_dir=args.journal_dir
    )
    for r in rows:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
