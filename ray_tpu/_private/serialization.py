"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Analogue of the reference's SerializationContext (reference:
python/ray/_private/serialization.py:149): cloudpickle for arbitrary
Python (lambdas, closures, classes), pickle-5 out-of-band buffers so large
numpy/jax host arrays are captured as contiguous memoryviews and written to
the shared-memory store without an extra copy.

ObjectRef semantics match the reference: refs nested inside values pickle
into reconstructable refs on the receiving side (ObjectRef.__reduce__ in
ray_tpu.api); only *top-level* task arguments that are refs get resolved
to values before execution (core_worker builds those as by-ref args).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any

import cloudpickle

# Buffers at or above this size are kept out-of-band (zero-copy path).
OUT_OF_BAND_MIN = 4096


@dataclass
class Serialized:
    """A serialized object: in-band pickle stream + out-of-band buffers."""

    inband: bytes
    buffers: list = field(default_factory=list)

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(b) for b in self.buffers)

    def materialize_buffers(self) -> "Serialized":
        return Serialized(self.inband, [bytes(b) for b in self.buffers])


def serialize(value: Any) -> Serialized:
    buffers: list[memoryview] = []

    def buffer_callback(buf) -> bool:
        raw = buf.raw()
        if raw.nbytes >= OUT_OF_BAND_MIN:
            buffers.append(raw)
            return False  # keep out-of-band
        return True  # small: keep in-band

    sink = io.BytesIO()
    cloudpickle.CloudPickler(
        sink, protocol=5, buffer_callback=buffer_callback
    ).dump(value)
    return Serialized(sink.getvalue(), buffers)


def deserialize(inband: bytes, buffers: list | None = None) -> Any:
    return pickle.loads(inband, buffers=buffers or [])
