"""Self-signed TLS material for cluster transport encryption.

(reference: the reference pairs its token validator with gRPC TLS,
src/ray/rpc/authentication/authentication_token_validator.h:26 +
grpc_server TLS options; here one self-signed cert is generated at
`start --head --tls`, servers present it, and every client PINS it —
no CA hierarchy, which is the right trust model for a single-operator
cluster: possession of the cert file is the trust root, and the auth
token never crosses the wire in cleartext.)
"""

from __future__ import annotations

import datetime
import ipaddress
import os


def generate_self_signed(cert_path: str, key_path: str) -> None:
    """Write a fresh self-signed cert + key valid for any host/IP (the
    cert is pinned by clients, so SAN breadth is not a weakness)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "ray_tpu-cluster")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=3650))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("*"),
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    for path, data, mode in (
        (
            key_path,
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
            0o600,
        ),
        (cert_path, cert.public_bytes(serialization.Encoding.PEM), 0o644),
    ):
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
