"""Chaos-testing utilities (reference: python/ray/_private/test_utils.py
— ResourceKillerActor :1412, RayletKiller :1534, WorkerKillerActor :1646
kill cluster components on an interval to exercise fault tolerance; the
nightly chaos suites build on them, release/nightly_tests/chaos_test/).
"""

from __future__ import annotations

import asyncio
import random
import time


def parse_straggler_spec(spec: str) -> "dict[int, float]":
    """Parse the RAY_TPU_STRAGGLER_DELAY chaos spec (same comma-
    separated env-spec family as RAY_TPU_RPC_FAILURE):
    ``"rank:seconds[,rank:seconds,…]"`` — the named collective ranks
    sleep that long before contributing to every op. Example:
    ``"2:0.5"`` makes rank 2 half a second late to each collective;
    the partial-allreduce tests use it to skip a deterministic rank.
    Malformed entries are ignored (chaos must never crash the op)."""
    out: dict[int, float] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        rank, _, delay = entry.partition(":")
        try:
            out[int(rank)] = float(delay)
        except ValueError:
            continue
    return out


def straggler_delay_for_rank(rank: int) -> float:
    """This rank's injected pre-contribution delay (0.0 = none). Read
    per call so tests can flip RAY_TPU_STRAGGLER_DELAY at runtime."""
    from ray_tpu._private import config

    spec = config.get("STRAGGLER_DELAY")
    if not spec:
        return 0.0
    return parse_straggler_spec(spec).get(rank, 0.0)


def parse_head_stall_spec(spec: str) -> "dict[str, float]":
    """Parse the RAY_TPU_HEAD_STALL chaos spec (same comma-separated
    env-spec family as RAY_TPU_STRAGGLER_DELAY):
    ``"method:seconds[,method:seconds,…]"`` — the head sleeps that long
    inside each matching RPC handler before dispatch. ``"*"`` matches
    any method; the pseudo-method ``"fold"`` stalls the background
    telemetry fold worker instead (the deterministic way to back up the
    bounded fold queue). Malformed entries are ignored (chaos must
    never crash the head)."""
    out: dict[str, float] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        method, _, secs = entry.partition(":")
        try:
            out[method] = float(secs)
        except ValueError:
            continue
    return out


def head_stall_for(method: str) -> float:
    """Injected latency for one head RPC (0.0 = none). Read per call so
    tests can flip RAY_TPU_HEAD_STALL at runtime."""
    from ray_tpu._private import config

    spec = config.get("HEAD_STALL")
    if not spec:
        return 0.0
    stalls = parse_head_stall_spec(spec)
    return stalls.get(method, stalls.get("*", 0.0))


def parse_slice_fail_spec(spec: str) -> "dict[int, tuple[str, float]]":
    """Parse the RAY_TPU_SLICE_FAIL chaos spec (same comma-separated
    env-spec family as RAY_TPU_STRAGGLER_DELAY): ``"slice:when[,…]"``
    where ``when`` is either

    - a float — every rank of that slice is delayed that many seconds
      per op (the whole slice becomes a straggler): ``("delay", s)``;
    - ``kill`` or ``kill@<after_s>`` — every rank of that slice is
      SIGKILLed (after ``after_s`` seconds from the first chaos check):
      ``("kill", after_s)``.

    ``"1:0.5"`` makes slice 1 half a second late to every collective;
    ``"1:kill@2"`` takes slice 1 down two seconds in. Malformed entries
    are ignored (chaos must never crash the op)."""
    out: "dict[int, tuple[str, float]]" = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        sl, _, when = entry.partition(":")
        try:
            idx = int(sl)
        except ValueError:
            continue
        when = when.strip()
        if when.startswith("kill"):
            _, _, after = when.partition("@")
            try:
                out[idx] = ("kill", float(after) if after else 0.0)
            except ValueError:
                continue
        else:
            try:
                out[idx] = ("delay", float(when))
            except ValueError:
                continue
    return out


def slice_fail_action(slice_index: int) -> "tuple[str, float] | None":
    """This slice's injected failure (None = healthy). Read per call so
    tests can flip RAY_TPU_SLICE_FAIL at runtime."""
    from ray_tpu._private import config

    spec = config.get("SLICE_FAIL")
    if not spec:
        return None
    return parse_slice_fail_spec(spec).get(int(slice_index))


# First time THIS process consulted the slice-fail clock: 'kill@2' means
# two seconds after the process first checks, so every rank of the slice
# dies deterministically relative to its own participation, not boot.
_slice_fail_t0: "float | None" = None


def maybe_fail_slice(slice_index: "int | None" = None) -> None:
    """Apply the RAY_TPU_SLICE_FAIL action for this process's slice:
    sleep for a "delay" spec, SIGKILL ourselves for a "kill" spec whose
    ``after_s`` has elapsed. ``slice_index`` defaults to this process's
    own slice (train context slice label, else the node's "slice"
    label); a process that cannot resolve its slice is never failed.
    Train loops under slice-chaos tests call this once per step — the
    in-process analogue of GCE reaping every host of the slice at
    once."""
    global _slice_fail_t0
    if slice_index is None:
        slice_index = own_slice_index()
    if slice_index is None:
        return
    action = slice_fail_action(slice_index)
    if action is None:
        return
    kind, val = action
    if kind == "delay":
        time.sleep(val)
        return
    if _slice_fail_t0 is None:
        _slice_fail_t0 = time.monotonic()
    if time.monotonic() - _slice_fail_t0 >= val:
        import os

        sigkill_pid(os.getpid())


def own_slice_index() -> "int | None":
    """This process's slice index: the train context's slice label when
    inside a train loop, else this node's "slice" label via the head
    node table. None when unresolvable (no chaos applies)."""
    label = None
    try:
        from ray_tpu.train import session

        ctx = session._context
        if ctx is not None and ctx.slice_label:
            label = ctx.slice_label
    # tpulint: allow(broad-except reason=chaos helper - a process without a train session simply falls through to the node-label lookup)
    except Exception:
        label = None
    if label is None:
        try:
            import ray_tpu.api as api

            rt = api._runtime
            node_addr = getattr(rt.core, "node_addr", None)
            if not node_addr:
                return None
            table = rt.run(rt.core.head.call("node_table"), 5)
            for n in table.values():
                if n.get("addr") == node_addr:
                    label = (n.get("labels") or {}).get("slice")
                    break
        # tpulint: allow(broad-except reason=chaos helper - an unresolvable slice means no chaos applies, never an op failure)
        except Exception:
            return None
    if label is None:
        return None
    try:
        return int(str(label).lstrip("s"))
    except ValueError:
        return None


def fake_hbm_cap_bytes() -> "int | None":
    """The RAY_TPU_FAKE_HBM_GB chaos cap in bytes (None = off). Read
    per call so tests can flip the knob at runtime: the memory sampler
    (runtime/memory.py) reports this as device capacity, driving
    headroom alerts and — when sampled usage exceeds it — the injected
    ResourceExhausted that exercises OOM forensics without real HBM
    pressure."""
    from ray_tpu._private import config

    gb = config.get("FAKE_HBM_GB")
    if not gb or gb <= 0:
        return None
    return int(float(gb) * (1 << 30))


def parse_preempt_spec(spec: str) -> "tuple[float, str]":
    """Parse the RAY_TPU_PREEMPT_AFTER_S chaos spec (same env-spec
    family as RAY_TPU_RPC_FAILURE): ``"<delay_s>[@<substr>]"`` — a
    synthetic preemption notice fires <delay_s> seconds after the node
    starts, on nodes whose node_id or addr contains <substr> (every
    node when omitted). Example: ``"2.5@a1b2c3"`` preempts the node
    whose id starts with a1b2c3 after 2.5s."""
    delay, _, substr = spec.partition("@")
    return float(delay), substr


class FakePreemptionSource:
    """Synthetic preemption-notice source (the test stand-in for the
    GCE maintenance-event poller): fires once, deterministically, per
    the RAY_TPU_PREEMPT_AFTER_S spec. Registered chaos tests use this
    to exercise the full drain lifecycle — notice → DRAINING → emergency
    checkpoint → replacement — without a cloud in sight."""

    interval_s = 0.1

    def __init__(self, spec: str):
        self.delay_s, self.substr = parse_preempt_spec(spec)
        self._t0 = time.monotonic()

    def poll(self, node) -> "tuple[str, float] | None":
        if self.substr and (
            self.substr not in node.node_id
            and self.substr not in (node.addr or "")
        ):
            return None
        if time.monotonic() - self._t0 < self.delay_s:
            return None
        from ray_tpu._private import config

        return ("synthetic-preemption", config.get("DRAIN_DEADLINE_S"))


def kill_one_replica(
    deployment_name: str, app_name: str = "default",
    index: int = 0,
) -> str:
    """SIGKILL the worker process hosting one serve replica — the
    deterministic replica-death chaos the serve control-plane tests and
    bench_serve's kill leg use (the serving twin of sigkill_pid's
    collective-rank kill). Picks the ``index``-th replica of the
    deployment's current routed list, reads the hosting worker's pid
    from its own get_stats, and SIGKILLs it. Returns the killed
    replica's actor id. Refuses to kill the calling process (inproc
    worker mode would take the test down with the replica)."""
    import os

    import ray_tpu
    from ray_tpu.runtime.core_worker import ActorSubmitTarget
    from ray_tpu.serve.handle import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    _version, replicas = ray_tpu.get(
        controller.get_replicas.remote(deployment_name, app_name)
    )
    if not replicas:
        raise RuntimeError(
            f"no replicas of {app_name}/{deployment_name} to kill"
        )
    actor_id, addr, _max_ongoing = replicas[index % len(replicas)]

    import ray_tpu.api as api

    rt = api._runtime
    refs = rt.run(
        rt.core.submit_task(
            "get_stats", (), {}, num_returns=1,
            actor=ActorSubmitTarget(actor_id, addr),
        )
    )
    stats = rt.run(rt.core.get(refs, timeout=10))[0]
    pid = stats.get("pid")
    if not pid:
        raise RuntimeError("replica reported no pid (old replica code?)")
    if pid == os.getpid():
        raise RuntimeError(
            "refusing to SIGKILL the calling process (inproc worker "
            "mode); run replica-kill chaos with subprocess workers"
        )
    sigkill_pid(int(pid))
    return actor_id


def sigkill_pid(pid: int) -> None:
    """SIGKILL one worker process — the targeted mid-op member killer
    the collective chaos tests use (WorkerKillerActor kills *random*
    leased workers; collective-abort assertions need to know which rank
    died). The node's reap loop notices within ~1s and the head fans the
    death out to the victim's collective groups."""
    import os
    import signal

    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass


class WorkerKillerActor:
    """Kills leased task workers on an interval. Deploy with
    ``ray_tpu.remote(WorkerKillerActor).remote(...)`` and call
    ``run.remote()``; tasks with retries should keep completing.

    By default actors are spared (killing the killer — or the test's own
    actors — makes assertions murky); pass ``include_actors=True`` for
    full chaos."""

    def __init__(
        self,
        interval_s: float = 1.0,
        max_kills: int = 3,
        include_actors: bool = False,
        seed: int = 0,
    ):
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.include_actors = include_actors
        self.kills: list[str] = []
        self._rng = random.Random(seed)

    async def _nodes(self):
        import ray_tpu.api as api

        rt = api._runtime
        table = await rt.core.head.call("node_table")
        return [n["addr"] for n in table.values()]

    async def run(self) -> list[str]:
        """Kill until max_kills; returns killed worker ids."""
        import ray_tpu.api as api

        rt = api._runtime
        while len(self.kills) < self.max_kills:
            await asyncio.sleep(self.interval_s)
            for addr in await self._nodes():
                try:
                    conn = await rt.core._connect(addr)
                    reply = await conn.call("list_workers")
                # tpulint: allow(broad-except reason=chaos actor probing nodes it may itself have killed; an unreachable node is skipped, which is the point)
                except Exception:  # noqa: BLE001 - node may be gone
                    continue
                victims = [
                    w for w in reply["workers"]
                    if w["leased"]
                    and (self.include_actors or not w["is_actor"])
                    and w["worker_id"] != rt.core.worker_id
                ]
                if not victims:
                    continue
                victim = self._rng.choice(victims)
                try:
                    await conn.call(
                        "kill_worker", worker_id=victim["worker_id"]
                    )
                    self.kills.append(victim["worker_id"])
                # tpulint: allow(broad-except reason=chaos kill racing the victim's own death; a lost race means the worker is already dead, try the next node)
                except Exception:  # noqa: BLE001
                    continue
                break
        return self.kills

    def kill_count(self) -> int:
        return len(self.kills)


class NodeKillerActor:
    """Tears down a whole (non-head) node daemon — the raylet-death
    chaos case (reference: RayletKiller test_utils.py:1534). Only nodes
    whose addresses are in ``targets`` are touched, so the test's own
    node survives."""

    def __init__(self, targets: list[str]):
        self.targets = list(targets)
        self.killed: list[str] = []

    async def kill_one(self) -> str | None:
        import ray_tpu.api as api

        rt = api._runtime
        while self.targets:
            addr = self.targets.pop(0)
            try:
                conn = await rt.core._connect(addr)
                # The node daemon has no self-destruct rpc: kill its
                # workers, then sever by asking the head to drop it is
                # not possible remotely — instead kill every worker so
                # leases fail over, which is the recoverable half of
                # node death testable in-process.
                reply = await conn.call("list_workers")
                for w in reply["workers"]:
                    await conn.call("kill_worker", worker_id=w["worker_id"])
                self.killed.append(addr)
                return addr
            # tpulint: allow(broad-except reason=chaos actor tearing down a node that may already be half-dead; the next target is tried)
            except Exception:  # noqa: BLE001
                continue
        return None
