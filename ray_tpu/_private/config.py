"""Central config registry (reference: the RAY_CONFIG X-macro list,
src/ray/common/ray_config_def.h — 228 typed knobs with env overrides —
and the `_system_config` dict `ray.init` threads through the GCS,
gcs_service.proto:642 GetInternalConfig).

Every knob is declared ONCE here with type, default, and doc. Resolution
order: programmatic override (init(system_config=...)) → environment
variable ``RAY_TPU_<NAME>`` → default. Worker processes inherit the
driver's overrides through the environment (set_system_config exports
them), the same propagation path the reference uses for its serialized
_system_config."""

from __future__ import annotations

import os
from typing import Any

# name → (type, default, doc). The env var is RAY_TPU_<NAME>.
CONFIG_DEFS: dict[str, tuple[type, Any, str]] = {
    # --- object store / spilling
    "POOL_BYTES": (int, 0, "shm pool capacity; 0 = auto (30% free, ≤2GiB)"),
    "DISABLE_NATIVE_STORE": (bool, False, "force the file-per-object store"),
    "SPILL_HIGH": (float, 0.8, "store usage fraction that triggers spilling"),
    "SPILL_LOW": (float, 0.5, "spill target usage fraction"),
    "SPILL_DIR": (str, "", "disk spill directory override"),
    # --- scheduling / memory
    "SCHED_TIMEOUT_S": (float, 60.0, "wait for autoscaler before failing "
                                     "an infeasible lease"),
    "MEMORY_THRESHOLD": (float, 0.95, "system memory fraction that "
                                      "triggers the OOM worker killer"),
    "HEALTH_TIMEOUT_S": (float, 30.0, "heartbeat silence before the head "
                                      "declares a node dead"),
    "FAKE_MEMORY_FRAC_FILE": (str, "", "test hook: read memory fraction "
                                       "from this file"),
    "FAKE_CHIPS": (str, "", "test hook: report this many TPU chips"),
    "NODE_LABELS": (str, "", "extra node labels as k=v,k=v"),
    "NODE_AGENT": (bool, True, "per-node dashboard agent (node-local "
                               "/healthz /api/stats /api/logs /metrics)"),
    "NODE_AGENT_HOST": (str, "127.0.0.1", "agent bind host — loopback "
                                          "by default: the agent has "
                                          "no auth, so expose it only "
                                          "behind your own proxy"),
    "MAX_LINEAGE_BYTES": (int, 512 << 20, "lineage byte budget per worker; "
                                          "oldest entries evict past it"),
    "WORKER_JAX_PLATFORMS": (str, "cpu", "JAX_PLATFORMS for spawned "
                                         "workers"),
    # --- compiled graphs
    "DAG_BUFFER_SIZE": (int, 256 * 1024, "channel slot capacity (bytes)"),
    "DAG_MAX_BUFFERED": (int, 8, "max in-flight executions per DAG"),
    "DAG_GET_TIMEOUT": (float, 30.0, "CompiledDAGRef.get timeout"),
    "DAG_SUBMIT_TIMEOUT": (float, 30.0, "execute() backpressure timeout"),
    # --- worker log pipeline
    "LOG_TO_DRIVER": (bool, True, "stream worker stdout/stderr to drivers "
                                  "via pubsub"),
    "LOG_DIR": (str, "", "worker log directory override"),
    # --- head fault tolerance
    "HEAD_JOURNAL": (str, "", "journal file for durable head state "
                              "(KV/actors/PGs); empty = off for "
                              "library init() (its session dir is "
                              "ephemeral), session default for CLI "
                              "daemons ('off' disables those too)"),
    "JOURNAL_FSYNC": (bool, False, "fsync every journal append (power-"
                                   "loss durability; default survives "
                                   "process crashes only)"),
    "JOURNAL_COMPACT_BYTES": (int, 8 << 20, "rewrite the head journal "
                                            "as one snapshot once it "
                                            "grows past this size"),
    "HEAD_RECONNECT_S": (float, 20.0, "how long clients retry head calls "
                                      "across a head restart"),
    # --- control-plane overload protection
    "HEAD_FOLD_QUEUE_MAX": (int, 20000, "bounded head telemetry fold "
                                        "queue: add_task_events batches "
                                        "queue here and fold off the "
                                        "dispatch path; when full the "
                                        "OLDEST events shed "
                                        "(ray_tpu_head_shed_total) "
                                        "rather than stall control RPCs"),
    "HEAD_SNAPSHOT_WATERMARK_BYTES": (int, 4 << 20, "journal bytes "
                                      "appended since the last snapshot "
                                      "before compaction fires "
                                      "regardless of the 2x floor "
                                      "guard — bounds restart-replay "
                                      "depth when the tables themselves "
                                      "are large (1000-node regime)"),
    "RPC_BACKOFF_BASE_S": (float, 0.2, "reconnect backoff base: attempt "
                                       "n sleeps uniform(0, min(cap, "
                                       "base*2^n)) — full jitter so a "
                                       "head restart's re-dial herd "
                                       "spreads instead of spiking"),
    "RPC_BACKOFF_MAX_S": (float, 5.0, "per-sleep cap on the jittered "
                                      "exponential reconnect backoff"),
    "RPC_RECONNECT_ATTEMPTS": (int, 64, "cap on reconnect attempts per "
                                        "call (0 = bounded only by the "
                                        "HEAD_RECONNECT_S deadline)"),
    "HEAD_NICE": (int, 0, "daemonized head only: renice the head "
                          "process to this value (e.g. -5) so control "
                          "RPCs win CPU contention against co-located "
                          "data-plane work; 0 = leave priority alone; "
                          "negative values need privileges and degrade "
                          "to a warning without them"),
    "HEAD_GC_FREEZE": (bool, True, "daemonized head only: gc.freeze() "
                                   "after boot + raised gen0 threshold "
                                   "— at 100k+ telemetry events/s the "
                                   "default (700,10,10) cadence runs "
                                   "full gen2 passes ~2/s, each "
                                   "scanning every module object plus "
                                   "the queued event dicts: tens-of-ms "
                                   "control-RPC tail spikes"),
    # --- rpc hardening
    "AUTH_TOKEN": (str, "", "shared-secret connection token; empty "
                            "disables auth (the start CLI generates one "
                            "by default — see scripts.py start)"),
    "TLS_CERT": (str, "", "path to a PEM cert: servers present it, "
                          "clients pin it (self-signed is fine; "
                          "`start --head --tls` generates one)"),
    "TLS_KEY": (str, "", "path to the PEM private key for TLS_CERT "
                         "(servers only)"),
    "RPC_MAX_FRAME": (int, 2 << 30, "largest accepted rpc frame (bytes)"),
    "WORKER_MODE": (str, "subprocess", "worker isolation: 'subprocess' "
                                       "(default) or 'inproc' — scale-"
                                       "simulation mode where workers "
                                       "are CoreWorkers on the node "
                                       "loop; the control plane "
                                       "(registration, leases, sync, "
                                       "journal) stays real, only "
                                       "process isolation is simulated"),
    # --- runtime envs
    "ENV_CACHE_BYTES": (int, 10 << 30, "built runtime-env cache budget; "
                                       "unreferenced envs evict oldest-"
                                       "idle-first past it"),
    "CPP_WORKER_CMD": (str, "", "command line for the C++ worker binary "
                                "(e.g. cpp/build/raytpu_worker); spawned "
                                "for leases whose runtime_env is "
                                "{'language': 'cpp'}"),
    # --- node drain / preemption
    "DRAIN_DEADLINE_S": (float, 30.0, "default drain notice window: how "
                                      "long a DRAINING node is expected "
                                      "to keep serving before it dies "
                                      "(GCE preemption notice is ~30s)"),
    "DRAIN_SIGTERM_LINGER_S": (float, 0.0, "how long a SIGTERMed node "
                                           "daemon keeps serving after "
                                           "self-reporting drain (0 = "
                                           "stop right after the "
                                           "notice; a second signal "
                                           "always cuts the linger "
                                           "short)"),
    "TRAIN_EMERGENCY_CHECKPOINT": (bool, True, "on a drain notice for "
                                               "this worker's node, "
                                               "report() raises "
                                               "PreemptedError once a "
                                               "checkpoint is in hand "
                                               "so the attempt resumes "
                                               "losing ≤1 step"),
    # --- sweep engine (tune)
    "TUNE_MAX_CONCURRENT": (int, 0, "trial gangs admitted at once "
                                    "(0 = as many as fit the healthy "
                                    "chip budget)"),
    "TUNE_ADMISSION_HEADROOM": (float, 0.0, "fraction of per-chip HBM "
                                            "the memory-planner "
                                            "admission check must "
                                            "leave free before a "
                                            "trial gang is admitted"),
    "TUNE_POLL_S": (float, 0.2, "sweep orchestrator poll interval: "
                                "ledger reads, rung checks, admission "
                                "retries"),
    "TUNE_INFRA_RETRIES": (int, 2, "re-admissions granted to a trial "
                                   "after an INFRA failure (worker/"
                                   "actor death); preemptions retry "
                                   "unconditionally, trial-code "
                                   "errors never do"),
    # --- distributed checkpoints
    "CKPT_REPLICATION": (int, 2, "total in-cluster copies of each "
                                 "checkpoint chunk (1 = local store "
                                 "only, no durability without a shared "
                                 "filesystem)"),
    "CKPT_CHUNK_BYTES": (int, 1 << 20, "content-addressed checkpoint "
                                       "chunk size (the dedup "
                                       "granularity)"),
    "CKPT_KEEP": (int, 2, "complete checkpoints retained per run in the "
                          "shard store; older manifests prune and their "
                          "unreferenced chunks are collected"),
    "CKPT_REPAIR_INTERVAL_S": (float, 2.0, "head repair-loop cadence for "
                                           "re-replicating under-"
                                           "replicated checkpoint "
                                           "chunks"),
    "CKPT_PERSIST_DELAY_S": (float, 0.0, "chaos spec: hold the window "
                                         "between chunk writes and the "
                                         "manifest commit open this "
                                         "long (kill-mid-save tests)"),
    "CKPT_ERASURE": (str, "", "'k,m' enables chunk-level erasure coding: "
                              "k data + m parity shards per group, "
                              "placed on distinct slices; any m losses "
                              "reconstruct ((k+m)/k bytes vs "
                              "replication's Nx). Empty = off"),
    "CKPT_VERIFY_READS": (bool, True, "re-hash every chunk on get_chunk; "
                                      "a mismatch is treated as a "
                                      "missing replica (corruption "
                                      "detection on the read path)"),
    "CKPT_CORRUPT": (str, "", "chaos spec: 'prefix:prob' — chunk reads "
                              "whose hash starts with prefix are "
                              "bit-flipped with probability prob "
                              "(deterministic per chunk), driving the "
                              "detect→reconstruct path"),
    "CKPT_REMOTE_TIER": (str, "", "remote spill tier for committed "
                                  "checkpoints: a directory path or "
                                  "file:// URI (FileTier), or gs:// "
                                  "(GCS, requires the cloud SDK). "
                                  "Empty = in-cluster only"),
    "CKPT_REMOTE_TIMEOUT_S": (float, 10.0, "deadline per remote-tier "
                                           "call; a slow or dead tier "
                                           "becomes a typed "
                                           "RemoteTierError, never a "
                                           "hang"),
    "REMOTE_TIER_FAIL": (str, "", "chaos spec: 'outage' (every tier call "
                                  "raises) or 'latency:<s>' (every tier "
                                  "call sleeps that long first; the "
                                  "deadline still applies)"),
    "OBJECT_DRAIN_EVACUATION": (bool, True, "on a drain notice, owners "
                                            "push sole-primary objects "
                                            "off the draining node to a "
                                            "healthy peer (or the "
                                            "remote tier when no peer "
                                            "fits)"),
    # --- misc
    "RPC_FAILURE": (str, "", "chaos spec: comma-separated method:prob "
                             "list ('*' matches any method)"),
    "HEAD_STALL": (str, "", "chaos spec: comma-separated "
                            "'method:seconds' — the head sleeps that "
                            "long inside each matching RPC handler "
                            "('*' matches any method, 'fold' stalls "
                            "the telemetry fold worker instead); "
                            "deterministic overload/starvation "
                            "injection for the admission-class tests"),
    "PREEMPT_AFTER_S": (str, "", "chaos spec: '<delay_s>[@<substr>]' — "
                                 "synthetic preemption notice: a node "
                                 "whose node_id/addr contains <substr> "
                                 "(every node when omitted) self-drains "
                                 "<delay_s> seconds after start"),
    "COLLECTIVE_TIMEOUT_S": (float, 60.0, "default collective deadline "
                                          "(rendezvous and per-op); "
                                          "group override via "
                                          "init_collective_group("
                                          "timeout_s=), per-op via the "
                                          "verb's timeout_s="),
    "COLLECTIVE_PARTIAL_GRACE_S": (float, 1.0, "default partial-mode "
                                               "sub-deadline past the "
                                               "fastest arrival "
                                               "(allreduce grace_s= "
                                               "overrides per op); "
                                               "fallback when the "
                                               "adaptive window has too "
                                               "few lag samples"),
    "COLLECTIVE_ADAPTIVE_GRACE": (bool, True, "derive the partial-mode "
                                              "grace window from the "
                                              "hub's straggler-lag "
                                              "histogram (p99 * 1.5, "
                                              "clamped to COLLECTIVE_"
                                              "GRACE_MIN/MAX_S) instead "
                                              "of the static default"),
    "COLLECTIVE_GRACE_MIN_S": (float, 0.1, "lower clamp for the "
                                           "adaptive grace window"),
    "COLLECTIVE_GRACE_MAX_S": (float, 10.0, "upper clamp for the "
                                            "adaptive grace window"),
    "COLLECTIVE_ALGO_CROSSOVER": (str, "", "tree-to-ring crossover "
                                           "override for algo='auto': "
                                           "a byte count ('65536') or "
                                           "per-world entries "
                                           "('2:65536,8:262144'); "
                                           "empty = built-in table"),
    "COLLECTIVE_COMPRESSION_BLOCK": (int, 256, "elements per absmax "
                                               "scale block of the "
                                               "int8 collective "
                                               "codec"),
    "COLLECTIVE_BUCKET_MB": (float, 4.0, "target gradient bucket size "
                                         "(MiB) for the bucketed "
                                         "overlap sync (collective/"
                                         "bucketer.py); ScalingConfig("
                                         "grad_bucket_mb=) overrides "
                                         "per trainer"),
    "STRAGGLER_DELAY": (str, "", "chaos spec: comma-separated "
                                 "'rank:seconds' — the named collective "
                                 "ranks sleep that long before every "
                                 "contribution (deterministic straggler "
                                 "injection, cpu backend)"),
    "SLICE_FAIL": (str, "", "chaos spec: comma-separated 'slice:when' — "
                            "'1:0.5' delays every rank of slice 1 by "
                            "0.5s per op (a whole-slice straggler); "
                            "'1:kill' / '1:kill@2' SIGKILLs every rank "
                            "of slice 1 (after 2s). The hierarchical "
                            "allreduce treats a killed slice as dead "
                            "(skipped in partial mode) and a delayed "
                            "slice as late"),
    "SLICE_FAULT_DOMAINS": (bool, True, "treat a slice as the unit of "
                                        "failure: a drain notice or "
                                        "unexpected death of any host "
                                        "of a slice drains the WHOLE "
                                        "slice, and the autoscaler "
                                        "provisions one replacement "
                                        "slice per draining slice "
                                        "instead of per node"),
    "COLLECTIVE_SKIP_DRAIN_THRESHOLD": (int, 10, "partial-collective "
                                                 "skips of one rank "
                                                 "within the sliding "
                                                 "window that escalate "
                                                 "it to the head as a "
                                                 "chronic straggler"),
    "COLLECTIVE_SKIP_WINDOW_S": (float, 60.0, "sliding window for the "
                                              "chronic-skip escalation "
                                              "threshold"),
    "COLLECTIVE_SKIP_DRAIN": (bool, True, "head drains a reported "
                                          "chronic straggler's node "
                                          "(drain-and-replace) instead "
                                          "of only flagging it"),
    "TRAIN_GOODPUT_ALERT_RATIO": (float, 0.5, "head warns (log + "
                                              "ray_tpu_train_goodput_"
                                              "alert gauge) when a "
                                              "job's stall+degraded "
                                              "fraction over the alert "
                                              "window exceeds this"),
    "TRAIN_GOODPUT_ALERT_WINDOW_S": (float, 60.0, "sliding window for "
                                                  "the goodput alert "
                                                  "ratio"),
    "TRACE": (bool, False, "enable span collection in every process"),
    "TRAIN_TELEMETRY": (bool, True, "train step-phase spans + goodput/"
                                    "MFU accounting (always-cheap; 0 "
                                    "makes step_span a pinned-budget "
                                    "no-op)"),
    "SERVE_TELEMETRY": (bool, True, "serve request-path spans (ingress/"
                                    "queue/prefill/decode) + TTFT/"
                                    "latency histograms + the head SLO "
                                    "ledger (always-cheap; 0 makes the "
                                    "per-request hooks pinned-budget "
                                    "no-ops)"),
    "SERVE_SLO_TTFT_S": (float, 2.0, "per-request time-to-first-token "
                                     "SLO target; streamed requests "
                                     "attain when TTFT is at or under "
                                     "it"),
    "SERVE_SLO_LATENCY_S": (float, 30.0, "per-request end-to-end "
                                         "latency SLO target (the "
                                         "attainment bound for unary "
                                         "requests, and a second bound "
                                         "for streams)"),
    "SERVE_SLO_TARGET": (float, 0.95, "required fraction of requests "
                                      "attaining their SLO over the "
                                      "window; below it the head warns "
                                      "and sets ray_tpu_serve_slo_"
                                      "alert"),
    "SERVE_SLO_WINDOW_S": (float, 60.0, "sliding window for serve SLO "
                                        "attainment and the burn-rate "
                                        "alert"),
    # --- serve control plane (autoscaling / drain / self-healing)
    "SERVE_AUTOSCALE": (bool, True, "controller policy loop consumes the "
                                    "serve signal plane (handle demand + "
                                    "head SLO ledger) and adjusts replica "
                                    "counts for deployments with an "
                                    "autoscaling_config; 0 freezes every "
                                    "target at its configured value"),
    "SERVE_AUTOSCALE_INTERVAL_S": (float, 1.0, "cadence of the "
                                               "controller's head serve-"
                                               "ledger poll (attainment "
                                               "+ request rate feeding "
                                               "scale decisions)"),
    "SERVE_AUTOSCALE_UP_COOLDOWN_S": (float, 0.0, "minimum seconds "
                                                  "between scale-UPs of "
                                                  "one deployment "
                                                  "(per-deployment "
                                                  "upscale_delay_s "
                                                  "raises it)"),
    "SERVE_AUTOSCALE_DOWN_COOLDOWN_S": (float, 2.0, "desired must stay "
                                                    "below target for "
                                                    "this long before a "
                                                    "scale-down (per-"
                                                    "deployment "
                                                    "downscale_delay_s "
                                                    "raises it); the "
                                                    "anti-flap window"),
    "SERVE_AUTOSCALE_HYSTERESIS": (float, 0.1, "dead-band fraction: a "
                                               "desired count within "
                                               "hysteresis*target of "
                                               "the current target is "
                                               "treated as equal, so "
                                               "demand noise cannot "
                                               "flap large "
                                               "deployments"),
    "SERVE_AUTOSCALE_SLO_BOOST": (bool, True, "scale one replica above "
                                              "the demand-derived count "
                                              "while the head reports "
                                              "the deployment's SLO "
                                              "alert ON (bounded by "
                                              "max_replicas)"),
    "SERVE_DRAIN_TIMEOUT_S": (float, 30.0, "scale-down drain bound: a "
                                           "retiring replica stops "
                                           "accepting, finishes in-"
                                           "flight requests up to this "
                                           "long, then is killed "
                                           "(DeploymentConfig."
                                           "drain_timeout_s overrides "
                                           "per deployment)"),
    "SERVE_RETRY_MAX": (int, 3, "router re-dispatch cap after typed "
                                "replica deaths for one request "
                                "(at-least-once; non-idempotent callers "
                                "opt out via retry_on_failure=False)"),
    "SERVE_RETRY_BACKOFF_S": (float, 0.05, "base of the router's "
                                           "exponential per-retry "
                                           "backoff after a replica "
                                           "death (doubles per retry, "
                                           "capped at 1s)"),
    "SERVE_BREAKER_FAILURES": (int, 3, "consecutive typed failures that "
                                       "OPEN a replica's circuit "
                                       "breaker (the router stops "
                                       "picking it)"),
    "SERVE_BREAKER_RESET_S": (float, 2.0, "seconds an open breaker "
                                          "waits before HALF-OPEN (one "
                                          "probe request; success "
                                          "closes, failure re-opens)"),
    "SERVE_UNAVAILABLE_TIMEOUT_S": (float, 5.0, "how long the router "
                                                "waits with NO routable "
                                                "replica (none known, "
                                                "or all dead/draining/"
                                                "breaker-open) before "
                                                "raising the typed "
                                                "NoReplicaAvailableError"
                                                " the proxy maps to 503 "
                                                "+ Retry-After; "
                                                "saturated-but-alive "
                                                "replicas keep queueing "
                                                "instead"),
    "LLM_PREFILL_DELAY": (float, 0.0, "chaos spec: sleep this long "
                                      "inside every LLM engine prefill "
                                      "admission (deterministic TTFT "
                                      "injection for serve-tracing "
                                      "tests)"),
    "MEM_TELEMETRY": (bool, True, "device/host memory sampling + "
                                  "subsystem byte registration + OOM "
                                  "forensics (always-cheap; 0 makes "
                                  "the per-step sample and track() "
                                  "pinned-budget no-ops)"),
    "MEM_HEADROOM_ALERT_FRACTION": (float, 0.1, "headroom alert "
                                                "threshold: warn (log "
                                                "+ ray_tpu_mem_"
                                                "headroom_alert) when "
                                                "free device memory "
                                                "drops below this "
                                                "fraction of "
                                                "capacity"),
    "MEM_OOM_REPORT_DIR": (str, "", "directory for persisted OOM "
                                    "forensics JSON reports (default: "
                                    "<tmpdir>/ray_tpu_mem)"),
    # --- compiled-program profiler
    "PROFILE": (bool, True, "compiled-program profiler plane: the "
                            "per-step capture hook + profile:step "
                            "reporting (always-cheap; 0 makes the "
                            "step hook a pinned-budget no-op and "
                            "ignores capture requests)"),
    "PROFILE_DIR": (str, "", "directory for jax_profile / capture "
                             "traces (default: <tmpdir>/ray_tpu_"
                             "profile)"),
    "PROFILE_CAPTURE_STEPS": (int, 3, "steps wrapped in one on-device "
                                      "trace per profile_capture "
                                      "request"),
    "PROFILE_REGRESSION_PCT": (float, 25.0, "relative drift (percent) "
                                            "of any decomposition "
                                            "category's share vs the "
                                            "journaled fingerprint "
                                            "that flips ray_tpu_"
                                            "profile_regression_alert "
                                            "ON for the job"),
    "FAKE_HBM_GB": (float, 0.0, "chaos spec: cap the memory sampler's "
                                "reported device capacity at this many "
                                "GiB (0 = off) so headroom alerts and "
                                "the OOM-forensics path are "
                                "deterministically drivable without "
                                "real HBM pressure; sampled usage "
                                "above the cap raises an injected "
                                "ResourceExhausted at step close"),
    "ADDRESS": (str, "", "default cluster address for init()"),
}

_overrides: dict[str, Any] = {}


def _coerce(name: str, raw: str) -> Any:
    typ = CONFIG_DEFS[name][0]
    if typ is bool:
        return raw not in ("", "0", "false", "False")
    return typ(raw)


def get(name: str) -> Any:
    """Resolved value of a knob (override → env → default)."""
    if name not in CONFIG_DEFS:
        raise KeyError(
            f"unknown config {name!r}; known: {sorted(CONFIG_DEFS)}"
        )
    if name in _overrides:
        return _overrides[name]
    raw = os.environ.get(f"RAY_TPU_{name}")
    if raw is not None:
        try:
            return _coerce(name, raw)
        except ValueError as e:
            # Fail LOUD: silently falling back to the default would let
            # an operator believe a malformed threshold applied.
            raise ValueError(
                f"malformed RAY_TPU_{name}={raw!r}: expected "
                f"{CONFIG_DEFS[name][0].__name__}"
            ) from e
    return CONFIG_DEFS[name][1]


def clear_system_config(*names: str) -> None:
    """Remove programmatic overrides AND their env exports (tests that
    set_system_config must clear both — popping only _overrides leaves
    the env var, which get() still resolves)."""
    for name in names:
        _overrides.pop(name, None)
        os.environ.pop(f"RAY_TPU_{name}", None)


def set_system_config(config: dict[str, Any]) -> None:
    """Programmatic overrides (reference: ray.init(_system_config=...)).
    Also exported to the environment so spawned workers inherit them."""
    unknown = set(config) - set(CONFIG_DEFS)
    if unknown:
        raise KeyError(
            f"unknown config {sorted(unknown)}; known: {sorted(CONFIG_DEFS)}"
        )
    # Coerce EVERYTHING before applying anything: a name or value error
    # mid-apply must not leave earlier overrides (and env exports)
    # behind.
    coerced: dict[str, Any] = {}
    for name, value in config.items():
        typ = CONFIG_DEFS[name][0]
        if isinstance(value, str):
            # Strings coerce with env semantics ("0"/"false" are falsy
            # for bool knobs — bool("0") would flip them ON).
            value = _coerce(name, value)
        elif not isinstance(value, typ):
            value = typ(value)
        coerced[name] = value
    for name, value in coerced.items():
        _overrides[name] = value
        os.environ[f"RAY_TPU_{name}"] = (
            ("1" if value else "0")
            if CONFIG_DEFS[name][0] is bool
            else str(value)
        )


def describe() -> dict[str, dict]:
    """Full registry with resolved values (surfaced by the CLI/state
    API the way the reference exposes GetInternalConfig)."""
    out = {}
    for name, (typ, default, doc) in CONFIG_DEFS.items():
        try:
            value = get(name)
        except ValueError as e:
            # The registry listing must render even with a malformed
            # env var — that is exactly when an operator needs it.
            value = f"<{e}>"
        out[name] = {
            "type": typ.__name__,
            "default": default,
            "value": value,
            "doc": doc,
            "env": f"RAY_TPU_{name}",
        }
    return out
