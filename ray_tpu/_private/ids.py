"""Binary IDs for jobs, tasks, actors, objects.

TPU-native analogue of the reference's id scheme (reference:
src/ray/common/id.h; spec src/ray/design_docs/id_specification.md): IDs are
fixed-width random byte strings; an ObjectID embeds the TaskID that creates
it plus a return index, giving every object a lineage pointer by
construction.
"""

from __future__ import annotations

import os

_UNIQUE_LEN = 16  # bytes of entropy for task/actor/job ids
_INDEX_LEN = 4  # big-endian return index suffix for object ids


class BaseID:
    __slots__ = ("_bytes",)
    LENGTH = _UNIQUE_LEN

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.LENGTH:
            raise ValueError(
                f"{type(self).__name__} needs {self.LENGTH} bytes, "
                f"got {len(id_bytes)}"
            )
        self._bytes = id_bytes

    @classmethod
    def random(cls):
        return cls(os.urandom(cls.LENGTH))

    @classmethod
    def from_hex(cls, s: str):
        return cls(bytes.fromhex(s))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)"


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class FunctionID(BaseID):
    pass


class ObjectID(BaseID):
    """TaskID (16B) + big-endian return index (4B)."""

    LENGTH = _UNIQUE_LEN + _INDEX_LEN

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(_INDEX_LEN, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts use the high bit of the index space so they never collide
        # with return indices.
        return cls(
            task_id.binary()
            + (put_index | 0x8000_0000).to_bytes(_INDEX_LEN, "big")
        )

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_UNIQUE_LEN])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[_UNIQUE_LEN:], "big")
