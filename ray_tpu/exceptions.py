"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised; the original traceback is in the message and the
    original exception (when picklable) in ``.cause``."""

    cause: Exception | None = None


class WorkerDiedError(RayTpuError):
    """The worker executing a task died (all retries exhausted)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel()."""


class ActorDiedError(RayTpuError):
    """The actor's worker process is gone."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get timed out."""


class ObjectLostError(RayTpuError):
    """An object's value is unrecoverable (owner and copies gone)."""
