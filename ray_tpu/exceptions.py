"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised; the original traceback is in the message and the
    original exception (when picklable) in ``.cause``."""

    cause: Exception | None = None


class WorkerDiedError(RayTpuError):
    """The worker executing a task died (all retries exhausted)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel()."""


class ActorDiedError(RayTpuError):
    """The actor's worker process is gone."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get timed out."""


class ObjectLostError(RayTpuError):
    """An object's value is unrecoverable (owner and copies gone)."""


class PreemptedError(RayTpuError):
    """This worker's node is DRAINING (preemption notice / operator
    drain) and an emergency checkpoint was just persisted: the train
    attempt unwinds now, at a step boundary, so the controller resizes
    and resumes losing at most one step instead of the whole
    inter-checkpoint interval."""

    def __init__(
        self,
        node_id: str | None = None,
        reason: str = "",
        deadline_ts: float | None = None,
    ):
        self.node_id = node_id
        self.reason = reason
        self.deadline_ts = deadline_ts
        nid = (node_id or "?")[:12]
        super().__init__(
            f"node {nid}… is draining ({reason or 'no reason given'}); "
            "emergency checkpoint taken, unwinding the attempt"
        )
