"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised; the original traceback is in the message and the
    original exception (when picklable) in ``.cause``."""

    cause: Exception | None = None


class WorkerDiedError(RayTpuError):
    """The worker executing a task died (all retries exhausted)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel()."""


class ActorDiedError(RayTpuError):
    """The actor's worker process is gone."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get timed out."""


class ObjectLostError(RayTpuError):
    """An object's value is unrecoverable (owner and copies gone)."""


class ReplicaDrainingError(RayTpuError):
    """A serve replica refused a new request because it is draining
    (scale-down retirement): it finishes its in-flight requests and
    then exits. The router treats this as a re-route signal — the
    request lands on a non-draining replica and the client never sees
    it — so it is only user-visible when raised from a bare actor call
    that bypassed the handle router."""

    def __init__(self, deployment: str = ""):
        self.deployment = deployment
        super().__init__(
            f"replica of deployment {deployment!r} is draining and "
            "accepts no new requests (re-route to a live replica)"
        )


class NoReplicaAvailableError(RayTpuError):
    """The handle router found NO routable replica — none registered,
    or every one is dead, draining, or circuit-breaker-open — for
    longer than SERVE_UNAVAILABLE_TIMEOUT_S. Saturated-but-alive
    replicas never raise this (the request queues instead). The HTTP
    proxy maps it to 503 with a ``Retry-After`` header of
    ``retry_after_s``."""

    def __init__(self, deployment: str = "", app: str = "",
                 retry_after_s: float = 1.0):
        self.deployment = deployment
        self.app = app
        self.retry_after_s = retry_after_s
        super().__init__(
            f"no routable replica for {app}/{deployment} (all dead, "
            f"draining, or circuit-open); retry after "
            f"{retry_after_s:.1f}s"
        )


class PreemptedError(RayTpuError):
    """This worker's node is DRAINING (preemption notice / operator
    drain) and an emergency checkpoint was just persisted: the train
    attempt unwinds now, at a step boundary, so the controller resizes
    and resumes losing at most one step instead of the whole
    inter-checkpoint interval."""

    def __init__(
        self,
        node_id: str | None = None,
        reason: str = "",
        deadline_ts: float | None = None,
    ):
        self.node_id = node_id
        self.reason = reason
        self.deadline_ts = deadline_ts
        nid = (node_id or "?")[:12]
        super().__init__(
            f"node {nid}… is draining ({reason or 'no reason given'}); "
            "emergency checkpoint taken, unwinding the attempt"
        )
