"""Process-local drain-notice registry.

The head fans node-drain notices out on the "collective" pubsub channel
(the same channel PR 1's member-death fan-out uses, so any process that
already watches for collective deaths learns about drains with no extra
subscription). Each process records the notices here; the train session
reads them to decide on emergency checkpoints (`train.preemption_notice`)
and the typed `PreemptedError` unwind.

Notices are advisory state, not commands: a notice for a node this
process does not run on still matters (rank 0 persists the emergency
checkpoint for a peer's draining node), so the registry keeps every
node's notice and lets callers filter by node address.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

from ray_tpu.util.metrics import Counter

logger = logging.getLogger(__name__)

EVACUATED = Counter(
    "ray_tpu_objects_evacuated_total",
    "general (non-checkpoint) objects moved off a draining node, by "
    "outcome: 'peer' (owner pushed to a healthy node), 'remote_tier' "
    "(no peer fit), 'failed'",
    tag_keys=("outcome",),
)

# Keep an expired notice in the registry for a while (forensics: WHY is
# my node about to die / why did it drain), but stop reporting it as
# ACTIVE shortly after its deadline — a preemption scare that never
# killed the node must not keep forcing emergency checkpoints.
_EXPIRED_KEEP_S = 300.0
_ACTIVE_GRACE_S = 10.0

# node_id → {node_id, node_addr, reason, deadline_ts, since}
_notices: dict[str, dict] = {}

# Callbacks invoked with each freshly recorded notice (object owners
# hook drain-time evacuation here without stealing the one-per-channel
# pubsub handler slot the collective death watch owns).
_listeners: list[Callable[[dict], None]] = []


def add_listener(fn: Callable[[dict], None]) -> None:
    """Register a callback for future drain notices. Idempotent per
    function object; exceptions are logged, never propagated into the
    pubsub handler."""
    if fn not in _listeners:
        _listeners.append(fn)


def remove_listener(fn: Callable[[dict], None]) -> None:
    try:
        _listeners.remove(fn)
    except ValueError:
        pass


def record(msg: dict) -> None:
    """Fold one "node_draining" fan-out message into the registry."""
    node_id = msg.get("node_id")
    if not node_id:
        return
    now = time.time()
    deadline_ts = msg.get("deadline_ts")
    if deadline_ts is None:
        deadline_ts = now + float(msg.get("deadline_s") or 0.0)
    _notices[str(node_id)] = {
        "node_id": str(node_id),
        "node_addr": msg.get("node_addr"),
        "reason": msg.get("reason") or "",
        "deadline_ts": float(deadline_ts),
        "since": now,
    }
    for fn in list(_listeners):
        try:
            fn(dict(_notices[str(node_id)]))
        # tpulint: allow(broad-except reason=a listener bug must not break the registry or the pubsub handler that feeds it)
        except Exception:
            logger.warning("drain listener %r failed", fn, exc_info=True)


def clear(node_id: str | None) -> None:
    if node_id:
        _notices.pop(str(node_id), None)


def _prune() -> None:
    now = time.time()
    for nid, n in list(_notices.items()):
        if now > n["deadline_ts"] + _EXPIRED_KEEP_S:
            del _notices[nid]


def notices() -> dict[str, dict]:
    _prune()
    return {nid: dict(n) for nid, n in _notices.items()}


def _is_active(n: dict) -> bool:
    return time.time() <= n["deadline_ts"] + _ACTIVE_GRACE_S


def for_node_addr(node_addr: str | None) -> dict | None:
    """The ACTIVE notice for a specific node address (this process's
    own node, usually), or None."""
    if not node_addr:
        return None
    _prune()
    for n in _notices.values():
        if n.get("node_addr") == node_addr and _is_active(n):
            return dict(n)
    return None


def any_notice() -> dict | None:
    """Any ACTIVE notice, soonest deadline first (cluster-wide view —
    lets rank 0 checkpoint for a peer's draining node)."""
    _prune()
    live = [n for n in _notices.values() if _is_active(n)]
    if not live:
        return None
    return dict(min(live, key=lambda n: n["deadline_ts"]))


def reset() -> None:
    """Test hook: forget every notice and listener (process-local state
    otherwise leaks across in-process cluster fixtures)."""
    _notices.clear()
    _listeners.clear()
