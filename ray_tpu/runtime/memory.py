"""Device/host memory signal plane: HBM sampler, per-subsystem byte
attribution, headroom alerting, and OOM forensics.

The memory twin of the serve/goodput signal planes (PRs 2/9): every
byte of HBM and host RAM a subsystem pins is *accounted* (registration
hooks below), *alerted on* (headroom gauge + OFF→ON warn log), and
*explained on death* (a ResourceExhausted produces a ranked live-buffer
report instead of a bare stack trace). This is the instrument the
ZeRO-sharding work proves its capacity claim with — BENCH_8B's
``"peak_hbm_gb": null`` is exactly the blindness this removes.

Three data sources, in preference order:

1. ``device.memory_stats()`` where the backend exposes it
   (bytes_in_use / peak_bytes_in_use / bytes_limit);
2. ``jax.live_arrays()`` byte accounting where it doesn't (the axon
   case BENCH_8B hit) — per-buffer, attributable to the subsystem that
   registered/tagged it;
3. the registration ledger alone when jax itself is absent.

Host RSS comes from /proc/self/status (VmRSS).

Subsystems that own big buffers register them with :func:`track`
(returning a live :class:`Registration` they ``update()``/``close()``)
and optionally :func:`tag_arrays` so OOM forensics can name them:
trainer param/optimizer state (train/step.py), gradient-bucket scratch
(collective/bucketer.py), checkpoint host double-buffers
(checkpoint/saver.py, host-side), and paged-KV pools (llm/paged_kv.py).
Per-node samples ride the task-event pipeline as ``mem:sample`` spans;
the head folds them into the memory ledger (HeadService._mem_event →
``mem_stats`` RPC → /api/memory → ``ray_tpu mem``).

Chaos: ``RAY_TPU_FAKE_HBM_GB`` caps the reported capacity so headroom
alerts and the OOM-forensics path are deterministically drivable
without real HBM pressure (a sampled usage above the fake cap raises
:class:`FakeResourceExhausted` at step close).

Disable with RAY_TPU_MEM_TELEMETRY=0: :func:`track` hands back a
shared no-op registration and :func:`step_sample` returns immediately;
a perf-floor test pins the disabled path under 50µs/step.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time

from ray_tpu.util.metrics import Gauge

logger = logging.getLogger("ray_tpu.memory")

# The subsystem tag taxonomy (the `kind` label of ray_tpu_mem_hbm_bytes).
# "other" is the unattributed remainder of live bytes — a big "other" is
# itself a finding (an owner that never registered).
KINDS = (
    "params",
    "optimizer",
    "grads",
    "activations",
    "kv_cache",
    "collective_scratch",
    "other",
)

HBM_BYTES = Gauge(
    "ray_tpu_mem_hbm_bytes",
    "device memory bytes attributed per subsystem kind (params / "
    "optimizer / grads / activations / kv_cache / collective_scratch / "
    "other)",
    tag_keys=("kind",),
)
HBM_USED = Gauge(
    "ray_tpu_mem_hbm_used_bytes",
    "total device memory in use at the last sample",
)
HBM_PEAK = Gauge(
    "ray_tpu_mem_hbm_peak_bytes",
    "peak device memory in use observed by this process",
)
HBM_CAPACITY = Gauge(
    "ray_tpu_mem_hbm_capacity_bytes",
    "device memory capacity (backend bytes_limit, the device-kind "
    "table, or the RAY_TPU_FAKE_HBM_GB chaos cap)",
)
HBM_HEADROOM = Gauge(
    "ray_tpu_mem_headroom_bytes",
    "capacity minus used device bytes at the last sample (negative "
    "under the chaos cap = injected pressure)",
)
HOST_RSS = Gauge(
    "ray_tpu_mem_host_rss_bytes",
    "resident set size of this process (/proc/self/status VmRSS)",
)
HEADROOM_ALERT = Gauge(
    "ray_tpu_mem_headroom_alert",
    "1 when device headroom is below MEM_HEADROOM_ALERT_FRACTION of "
    "capacity (OFF→ON logs a warning)",
)

# Known HBM capacities by device-kind substring (public spec sheets;
# same family as telemetry.PEAK_FLOPS) — the fallback when the backend
# exposes no bytes_limit.
DEVICE_HBM_GB = {
    "v5e": 16.0,
    "v5litepod": 16.0,
    "v5 lite": 16.0,
    "v5p": 95.0,
    "v4": 32.0,
    "v6e": 32.0,
}


def enabled() -> bool:
    from ray_tpu._private import config

    return config.get("MEM_TELEMETRY")


class FakeResourceExhausted(MemoryError):
    """The injected stand-in for the backend's RESOURCE_EXHAUSTED:
    raised at step close when sampled usage exceeds the
    RAY_TPU_FAKE_HBM_GB chaos cap. Message-compatible with
    :func:`is_resource_exhausted` so every forensics path downstream
    treats it exactly like the real thing."""


def is_resource_exhausted(err: BaseException | None) -> bool:
    """True for the backend's OOM (XlaRuntimeError with a
    RESOURCE_EXHAUSTED status — jaxlib surfaces no stable class for
    it) and for the injected :class:`FakeResourceExhausted`."""
    if err is None:
        return False
    if isinstance(err, FakeResourceExhausted):
        return True
    name = type(err).__name__
    text = str(err)
    return (
        "RESOURCE_EXHAUSTED" in text
        or "ResourceExhausted" in name
        or ("Resource exhausted" in text and "Error" in name)
    )


# --------------------------------------------------------------- registry
class Registration:
    """One subsystem's live byte claim. ``update(nbytes)`` is a plain
    attribute store (hot-path cheap; gauges are set only at sample
    time); ``close()`` retires the claim."""

    __slots__ = ("tag", "kind", "device", "nbytes", "_provider",
                 "_closed", "_leak_box", "__weakref__")

    def __init__(self, tag, kind, device, nbytes, provider):
        self.tag = tag
        self.kind = kind
        self.device = device
        self.nbytes = int(nbytes)
        self._provider = provider
        self._closed = False
        # Sanitizer leak box (sanitize.watch_registration): close()
        # marks it, a GC while open warns — TPU404's runtime twin.
        self._leak_box = None

    def update(self, nbytes: int) -> None:
        self.nbytes = int(nbytes)

    def add(self, nbytes: int) -> None:
        self.nbytes += int(nbytes)

    def current_bytes(self) -> int:
        if self._provider is not None:
            try:
                return int(self._provider())
            # tpulint: allow(broad-except reason=a registration provider crashing must degrade to the last pushed byte count, never fail the sampler)
            except Exception:  # noqa: BLE001
                return self.nbytes
        return self.nbytes

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._leak_box is not None:
                self._leak_box["closed"] = True
            with _reg_lock:
                if _registry.get(self.tag) is self:
                    del _registry[self.tag]

    # Context-manager support: `with memory.track(...):` is the
    # structurally paired form TPU404 never flags.
    def __enter__(self) -> "Registration":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class _NoopRegistration:
    """Disabled-path registration: shared, allocation-free."""

    __slots__ = ()
    tag = ""
    kind = "other"
    device = True
    nbytes = 0

    def update(self, nbytes: int) -> None:
        pass

    def add(self, nbytes: int) -> None:
        pass

    def current_bytes(self) -> int:
        return 0

    def close(self) -> None:
        pass

    def __enter__(self) -> "_NoopRegistration":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_REG = _NoopRegistration()

_reg_lock = threading.Lock()
_registry: dict[str, Registration] = {}
# id(array) → (tag, kind, weakref): forensic attribution for live
# buffers. The weakref is KEPT in the entry (a dead ref never fires its
# callback) so the callback can drop the entry when the array dies —
# otherwise a recycled id() would misattribute a new array to an old
# tag. Arrays that refuse weakrefs are simply not tagged (they rank as
# "other").
_array_tags: dict[int, tuple] = {}


def track(
    tag: str,
    kind: str = "other",
    nbytes: int = 0,
    provider=None,
    device: bool = True,
):
    """Register a subsystem's byte claim. ``tag`` is the unique
    registration site (re-tracking a tag replaces the old claim — the
    re-init case); ``kind`` buckets it into the metric taxonomy;
    ``provider`` (optional zero-arg callable) is consulted at sample
    time instead of the pushed ``nbytes``. ``device=False`` claims are
    host-side (checkpoint double-buffers) and fold into the host
    section of the sample. Returns the live :class:`Registration`
    (the shared no-op when telemetry is disabled)."""
    if not enabled():
        return NOOP_REG
    reg = Registration(tag, kind, device, nbytes, provider)
    from ray_tpu._private import sanitize

    if sanitize.leaks_enabled():
        sanitize.watch_registration(reg)
    with _reg_lock:
        old = _registry.get(tag)
        _registry[tag] = reg
    if old is not None and old is not reg:
        # Re-tracking a tag replaces the claim; retire the old one so
        # its leak box doesn't cry wolf when it is collected.
        old.close()
    return reg


def tag_arrays(tag: str, kind: str, tree) -> None:
    """Attribute every array leaf of ``tree`` to (tag, kind) for OOM
    forensics. Weakref-based: tags die with their arrays."""
    if not enabled():
        return
    import weakref

    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except ImportError:
        leaves = tree if isinstance(tree, (list, tuple)) else [tree]
    for leaf in leaves:
        if not hasattr(leaf, "nbytes"):
            continue
        key = id(leaf)

        def _drop(_ref, _key=key):
            _array_tags.pop(_key, None)

        try:
            ref = weakref.ref(leaf, _drop)
        except TypeError:
            continue  # not weakref-able: stays unattributed
        _array_tags[key] = (tag, kind, ref)


def registered_bytes(device: bool = True) -> dict[str, int]:
    """Current claims folded by kind (device- or host-side)."""
    out: dict[str, int] = {}
    with _reg_lock:
        regs = list(_registry.values())
    for reg in regs:
        if reg.device is device:
            out[reg.kind] = out.get(reg.kind, 0) + reg.current_bytes()
    return out


def clear_registry() -> None:
    """Drop every registration and array tag (test isolation)."""
    with _reg_lock:
        _registry.clear()
    _array_tags.clear()
    global _live_peak, _alert_on
    _live_peak = 0
    _alert_on = False


# --------------------------------------------------------------- sampling
_live_peak = 0  # process-local peak of sampled used bytes
_alert_on = False


def _device_stats() -> dict | None:
    """Backend memory_stats() of device 0, or None where unexposed."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        return stats or None
    # tpulint: allow(broad-except reason=memory_stats probing; any backend without the API (axon) falls through to live-array accounting rather than failing the sample)
    except Exception:  # noqa: BLE001
        return None


def _live_array_bytes() -> int | None:
    try:
        import jax

        return int(sum(a.nbytes for a in jax.live_arrays()))
    # tpulint: allow(broad-except reason=live-array accounting fallback; a jax-less or mid-teardown process degrades to the registration ledger, never fails the sample)
    except Exception:  # noqa: BLE001
        return None


def device_capacity_bytes() -> tuple[int | None, str]:
    """(capacity, source): the RAY_TPU_FAKE_HBM_GB chaos cap, the
    backend's bytes_limit, or the device-kind table. (None, "unknown")
    when nothing answers."""
    from ray_tpu._private.test_utils import fake_hbm_cap_bytes

    fake = fake_hbm_cap_bytes()
    if fake is not None:
        return fake, "fake"
    stats = _device_stats()
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"]), "memory_stats"
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    # tpulint: allow(broad-except reason=device-kind probing for a capacity fallback; no devices means no capacity, which is the honest answer)
    except Exception:  # noqa: BLE001
        return None, "unknown"
    for name, gb in DEVICE_HBM_GB.items():
        if name in kind:
            return int(gb * (1 << 30)), "device_kind"
    return None, "unknown"


def host_rss_bytes() -> int | None:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def _node_ident() -> str:
    """Stable per-node identity for the head ledger fold. The node
    address when a runtime is up (one sampler per worker folds into one
    node row), else host:pid."""
    try:
        import ray_tpu.api as api

        core = getattr(api._runtime, "core", None)
        addr = getattr(core, "node_addr", None) if core else None
        if addr:
            return str(addr)
    # tpulint: allow(broad-except reason=node-identity probe outside a runtime; the host:pid fallback below is always valid)
    except Exception:  # noqa: BLE001
        pass
    return f"{socket.gethostname()}:{os.getpid()}"


def alert_fraction() -> float:
    from ray_tpu._private import config

    return config.get("MEM_HEADROOM_ALERT_FRACTION")


def sample(job: str | None = None, emit: bool = True) -> dict | None:
    """Take one memory sample: device used/peak/capacity with per-kind
    attribution, host RSS, headroom + alert state. Sets every gauge,
    runs the OFF→ON alert log, and (``emit=True``) ships a
    ``mem:sample`` span for the head ledger. Returns the sample dict,
    or None when telemetry is disabled."""
    global _live_peak, _alert_on
    if not enabled():
        return None
    now = time.time()
    by_kind = registered_bytes(device=True)
    reg_total = sum(by_kind.values())
    stats = _device_stats()
    if stats and stats.get("bytes_in_use"):
        used = int(stats["bytes_in_use"])
        peak = int(stats.get("peak_bytes_in_use") or used)
        source = "memory_stats"
    else:
        live = _live_array_bytes()
        if live is not None:
            used = max(live, reg_total)
            source = "live_arrays"
        else:
            used = reg_total
            source = "registered"
        _live_peak = max(_live_peak, used)
        peak = _live_peak
    by_kind["other"] = max(0, used - reg_total)
    capacity, cap_source = device_capacity_bytes()
    headroom = capacity - used if capacity is not None else None
    host = {
        "rss_bytes": host_rss_bytes(),
        "by_kind": registered_bytes(device=False),
    }
    alert = bool(
        capacity
        and headroom is not None
        and headroom < capacity * alert_fraction()
    )
    if alert and not _alert_on:
        logger.warning(
            "device memory headroom low: %.2f GiB free of %.2f GiB "
            "(alert below %.0f%%) — top kinds: %s",
            (headroom or 0) / (1 << 30), capacity / (1 << 30),
            100.0 * alert_fraction(),
            ", ".join(
                f"{k}={v / (1 << 30):.2f}GiB"
                for k, v in sorted(
                    by_kind.items(), key=lambda kv: -kv[1]
                )[:3]
            ),
        )
    _alert_on = alert
    for kind, nbytes in by_kind.items():
        HBM_BYTES.set(float(nbytes), tags={"kind": kind})
    HBM_USED.set(float(used))
    HBM_PEAK.set(float(peak))
    if capacity is not None:
        HBM_CAPACITY.set(float(capacity))
        HBM_HEADROOM.set(float(headroom))
    if host["rss_bytes"] is not None:
        HOST_RSS.set(float(host["rss_bytes"]))
    HEADROOM_ALERT.set(1.0 if alert else 0.0)
    rec = {
        "ts": now,
        "node": _node_ident(),
        "job": job,
        "hbm": {
            "used_bytes": used,
            "peak_bytes": peak,
            "capacity_bytes": capacity,
            "headroom_bytes": headroom,
            "by_kind": by_kind,
            "source": source,
            "capacity_source": cap_source,
        },
        "host": host,
        "alert": alert,
    }
    if emit:
        from ray_tpu.util import tracing

        tracing.emit_span(
            "mem:sample", now, 0.0,
            mem_node=rec["node"],
            mem_job=job,
            mem_used_bytes=used,
            mem_peak_bytes=peak,
            mem_capacity_bytes=capacity,
            mem_host_rss_bytes=host["rss_bytes"],
            mem_by_kind={k: v for k, v in by_kind.items() if v},
        )
    return rec


def step_sample(ctx) -> dict | None:
    """Per-step sampling hook (train/telemetry.py calls it at step
    close): one sample tagged with the job, then the chaos-cap OOM
    check — a sampled usage above RAY_TPU_FAKE_HBM_GB raises
    :class:`FakeResourceExhausted` *after* persisting its own forensics
    report, so the injected death leaves the same evidence a real one
    would."""
    if not enabled():
        return None
    job = getattr(ctx, "experiment_name", None)
    rec = sample(job=job)
    if rec is None:
        return None
    cap = rec["hbm"]["capacity_bytes"]
    if (
        rec["hbm"]["capacity_source"] == "fake"
        and cap
        and rec["hbm"]["used_bytes"] > cap
    ):
        err = FakeResourceExhausted(
            f"RESOURCE_EXHAUSTED: injected OOM — "
            f"{rec['hbm']['used_bytes']} bytes in use over the "
            f"RAY_TPU_FAKE_HBM_GB cap of {cap} bytes"
        )
        on_resource_exhausted(err, job=job)
        raise err
    return rec


# ----------------------------------------------------------- OOM forensics
def oom_report(top_n: int = 10) -> dict:
    """Ranked live-buffer report: the top-N live device buffers by
    nbytes (shape, dtype, owning subsystem tag) plus per-kind totals
    and the current sample — the "what ate the HBM" answer."""
    buffers = []
    try:
        import jax

        live = list(jax.live_arrays())
    # tpulint: allow(broad-except reason=forensics on a dying process; an unenumerable backend still gets the registration-ledger half of the report)
    except Exception:  # noqa: BLE001
        live = []
    for arr in live:
        tag, kind = _array_tags.get(id(arr), ("", "other"))[:2]
        try:
            buffers.append({
                "nbytes": int(arr.nbytes),
                "shape": list(getattr(arr, "shape", ())),
                "dtype": str(getattr(arr, "dtype", "?")),
                "tag": tag,
                "kind": kind,
            })
        # tpulint: allow(broad-except reason=one half-deleted buffer must not abort the whole OOM report)
        except Exception:  # noqa: BLE001
            continue
    buffers.sort(key=lambda b: -b["nbytes"])
    totals: dict[str, int] = {}
    for b in buffers:
        totals[b["kind"]] = totals.get(b["kind"], 0) + b["nbytes"]
    return {
        "buffers": buffers[:top_n],
        "live_buffers": len(buffers),
        "live_bytes": sum(b["nbytes"] for b in buffers),
        "bytes_by_kind": totals,
        "registered_by_kind": registered_bytes(device=True),
        "sample": sample(emit=False),
    }


def _report_dir() -> str:
    from ray_tpu._private import config

    d = config.get("MEM_OOM_REPORT_DIR")
    if not d:
        import tempfile

        d = os.path.join(tempfile.gettempdir(), "ray_tpu_mem")
    os.makedirs(d, exist_ok=True)
    return d


def on_resource_exhausted(
    err: BaseException, job: str | None = None, top_n: int = 10
) -> str | None:
    """OOM forensics: build the ranked report, emit it as a ``mem:oom``
    span, persist it as JSON, and log the top consumer. Idempotent per
    error object (the injection path and the trainer's catch may both
    see the same exception). Returns the report path (None when
    telemetry is disabled)."""
    if not enabled():
        return None
    existing = getattr(err, "_mem_forensics_path", None)
    if existing is not None:
        return existing
    rep = oom_report(top_n=top_n)
    rep["error"] = f"{type(err).__name__}: {err}"[:500]
    rep["job"] = job
    now = time.time()
    path = os.path.join(
        _report_dir(), f"oom-{int(now)}-{os.getpid()}.json"
    )
    try:
        with open(path, "w") as f:
            json.dump(rep, f, indent=2)
    except OSError:
        path = None
    top = rep["buffers"][0] if rep["buffers"] else None
    logger.warning(
        "ResourceExhausted forensics: %d live buffers, %.2f GiB live; "
        "top consumer %s (%s, %.2f GiB); report: %s",
        rep["live_buffers"], rep["live_bytes"] / (1 << 30),
        (top or {}).get("tag") or (top or {}).get("kind") or "?",
        (top or {}).get("dtype", "?"),
        ((top or {}).get("nbytes") or 0) / (1 << 30),
        path or "<unwritable>",
    )
    from ray_tpu.util import tracing

    tracing.emit_span(
        "mem:oom", now, 0.0,
        mem_node=_node_ident(),
        mem_job=job,
        mem_error=rep["error"],
        mem_live_bytes=rep["live_bytes"],
        mem_top=[
            {k: b[k] for k in ("nbytes", "kind", "tag", "dtype")}
            for b in rep["buffers"][:3]
        ],
        mem_report_path=path,
    )
    try:
        err._mem_forensics_path = path
    except AttributeError:
        pass  # exceptions with __slots__: forensics just reruns
    return path
